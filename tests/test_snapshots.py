"""Snapshot/restore (VERDICT r2 next #7): directory blob store, incremental
by segment identity, restore into a new index with identical results."""

import json
import os

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import ResourceAlreadyExistsError
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.snapshots.repository import SnapshotMissingError

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon"]


@pytest.fixture()
def node(tmp_path):
    node = Node()
    node.snapshots.put_repository("backup", "fs",
                                  {"location": str(tmp_path / "repo")})
    yield node
    node.close()


def fill(node, index="src", n=200, shards=2):
    node.create_index(index, {
        "settings": {"index": {"number_of_shards": shards}},
        "mappings": {"properties": {"body": {"type": "text"},
                                    "n": {"type": "integer"}}}})
    svc = node.indices.get(index)
    rng = np.random.default_rng(5)
    for i in range(n):
        words = rng.choice(WORDS, size=int(rng.integers(3, 9)))
        svc.index_doc(str(i), {"body": " ".join(words), "n": i})
    svc.refresh()
    return svc


def results(svc, body=None):
    r = svc.search(body or {"query": {"match": {"body": "alpha beta"}},
                           "size": 30, "track_total_hits": True})
    return ([(h["_id"], round(h["_score"], 5)) for h in r["hits"]["hits"]],
            r["hits"]["total"]["value"])


def test_snapshot_delete_restore_identical(node):
    svc = fill(node)
    for i in range(0, 40, 3):
        svc.delete_doc(str(i))
    svc.refresh()
    want = results(svc)
    meta = node.snapshots.create("backup", "snap1", ["src"])
    assert meta["state"] == "SUCCESS"
    node.delete_index("src")
    assert not node.indices.has("src")
    node.snapshots.restore("backup", "snap1")
    got = results(node.indices.get("src"))
    assert got == want
    # restored engine keeps indexing: writes after restore work
    node.indices.get("src").index_doc("new", {"body": "alpha", "n": 999})
    node.indices.get("src").refresh()
    assert node.indices.get("src").get_doc("new") is not None


def test_second_snapshot_reuses_unchanged_segments(node, tmp_path):
    svc = fill(node)
    node.snapshots.create("backup", "snap1", ["src"])
    blobs_dir = str(tmp_path / "repo" / "blobs")
    n_blobs_1 = len(os.listdir(blobs_dir))
    # no changes: second snapshot writes ZERO new segment blobs
    meta2 = node.snapshots.create("backup", "snap2", ["src"])
    assert len(os.listdir(blobs_dir)) == n_blobs_1
    assert meta2["stats"]["segments_reused"] == meta2["stats"]["segments"]
    # add docs -> only the NEW segment is written
    svc.index_doc("x1", {"body": "alpha zeta", "n": 1})
    svc.refresh()
    node.snapshots.create("backup", "snap3", ["src"])
    n_blobs_3 = len(os.listdir(blobs_dir))
    assert n_blobs_1 < n_blobs_3 <= n_blobs_1 + 2


def test_restore_with_rename(node):
    svc = fill(node, n=60, shards=1)
    want = results(svc)
    node.snapshots.create("backup", "snap1", ["src"])
    r = node.snapshots.restore("backup", "snap1",
                               rename_pattern="src",
                               rename_replacement="copy")
    assert r["snapshot"]["indices"] == ["copy"]
    assert results(node.indices.get("copy")) == want
    assert node.indices.has("src")   # original untouched
    with pytest.raises(ResourceAlreadyExistsError):
        node.snapshots.restore("backup", "snap1")   # src still exists


def test_delete_snapshot_gc(node, tmp_path):
    svc = fill(node, n=50, shards=1)
    node.snapshots.create("backup", "a", ["src"])
    svc.index_doc("y", {"body": "beta", "n": 7})
    svc.refresh()
    node.snapshots.create("backup", "b", ["src"])
    blobs_dir = str(tmp_path / "repo" / "blobs")
    n_all = len(os.listdir(blobs_dir))
    node.snapshots.delete("backup", "b")
    # b's extra segment GC'd; a's blobs survive
    assert len(os.listdir(blobs_dir)) < n_all
    node.delete_index("src")
    node.snapshots.restore("backup", "a")
    assert node.indices.get("src").doc_count() == 50
    with pytest.raises(SnapshotMissingError):
        node.snapshots.get("backup", "b")


def test_snapshot_rest_roundtrip(node, tmp_path):
    from elasticsearch_tpu.rest import RestController, register_handlers

    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None):
        raw = json.dumps(body).encode() if body is not None else None
        resp = rc.dispatch(method, path, {}, raw)
        return resp.status, json.loads(resp.encode() or b"{}")

    fill(node, index="ri", n=30, shards=1)
    st, _ = call("PUT", "/_snapshot/r2",
                 {"type": "fs", "settings": {"location": str(tmp_path / "r2")}})
    assert st == 200
    st, body = call("PUT", "/_snapshot/r2/s1", {"indices": "ri"})
    assert st == 200 and body["snapshot"]["state"] == "SUCCESS"
    st, body = call("GET", "/_snapshot/r2/s1")
    assert st == 200 and body["snapshots"][0]["indices"] == ["ri"]
    st, body = call("POST", "/_snapshot/r2/s1/_restore",
                    {"rename_pattern": "ri", "rename_replacement": "ri2"})
    assert st == 200
    assert node.indices.get("ri2").doc_count() == 30
    st, _ = call("DELETE", "/_snapshot/r2/s1")
    assert st == 200
    st, _ = call("GET", "/_snapshot/r2/s1")
    assert st == 404

"""Parent-join: join field + has_child/has_parent/parent_id (VERDICT r4
item 6; ref: modules/parent-join/)."""

import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.errors import ElasticsearchTpuError
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture(scope="module")
def svc():
    meta = IndexMetadata(
        index="jn", uuid="u_jn", settings=Settings({}),
        mappings={"properties": {
            "jf": {"type": "join",
                   "relations": {"question": "answer"}},
            "body": {"type": "text"},
            "votes": {"type": "integer"},
        }})
    svc = IndexService(meta)
    svc.index_doc("q1", {"jf": "question", "body": "how do tpus work"})
    svc.index_doc("q2", {"jf": {"name": "question"},
                         "body": "what is xla"})
    svc.index_doc("q3", {"jf": "question", "body": "unanswered question"})
    svc.index_doc("a1", {"jf": {"name": "answer", "parent": "q1"},
                         "body": "systolic arrays", "votes": 7})
    svc.index_doc("a2", {"jf": {"name": "answer", "parent": "q1"},
                         "body": "matrix units", "votes": 2})
    svc.index_doc("a3", {"jf": {"name": "answer", "parent": "q2"},
                         "body": "a compiler", "votes": 5})
    svc.refresh()
    yield svc
    svc.close()


def _ids(r):
    return sorted(h["_id"] for h in r["hits"]["hits"])


def test_has_child_matches_parents(svc):
    r = svc.search({"query": {"has_child": {
        "type": "answer", "query": {"match": {"body": "arrays"}}}}})
    assert _ids(r) == ["q1"]
    r2 = svc.search({"query": {"has_child": {
        "type": "answer", "query": {"match_all": {}}}}})
    assert _ids(r2) == ["q1", "q2"]     # q3 has no children


def test_has_child_min_children(svc):
    r = svc.search({"query": {"has_child": {
        "type": "answer", "query": {"match_all": {}},
        "min_children": 2}}})
    assert _ids(r) == ["q1"]


def test_has_child_score_modes(svc):
    for mode, expect in [("sum", 2.0), ("max", 1.0), ("avg", 1.0)]:
        r = svc.search({"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}},
            "score_mode": mode}}})
        q1 = [h for h in r["hits"]["hits"] if h["_id"] == "q1"][0]
        assert abs(q1["_score"] - expect) < 1e-5, mode


def test_has_parent_matches_children(svc):
    r = svc.search({"query": {"has_parent": {
        "parent_type": "question", "query": {"match": {"body": "xla"}}}}})
    assert _ids(r) == ["a3"]


def test_parent_id(svc):
    r = svc.search({"query": {"parent_id": {"type": "answer",
                                            "id": "q1"}}})
    assert _ids(r) == ["a1", "a2"]


def test_join_relation_name_is_term_searchable(svc):
    r = svc.search({"query": {"term": {"jf": "question"}}, "size": 10})
    assert _ids(r) == ["q1", "q2", "q3"]


def test_join_combines_with_bool(svc):
    r = svc.search({"query": {"bool": {
        "must": [{"has_child": {"type": "answer",
                                "query": {"range": {"votes": {"gte": 6}}}}}],
    }}})
    assert _ids(r) == ["q1"]


def test_join_respects_child_deletes(svc):
    meta = IndexMetadata(
        index="jn2", uuid="u_jn2", settings=Settings({}),
        mappings={"properties": {
            "jf": {"type": "join", "relations": {"p": "c"}}}})
    s2 = IndexService(meta)
    s2.index_doc("p1", {"jf": "p"})
    s2.index_doc("c1", {"jf": {"name": "c", "parent": "p1"}})
    s2.refresh()
    s2.delete_doc("c1")
    s2.refresh()
    r = s2.search({"query": {"has_child": {
        "type": "c", "query": {"match_all": {}}}}})
    assert _ids(r) == []
    s2.close()


def test_join_child_without_parent_rejected(svc):
    with pytest.raises(ElasticsearchTpuError):
        svc.index_doc("bad", {"jf": {"name": "answer"}})


def test_join_unknown_relation_rejected(svc):
    with pytest.raises(ElasticsearchTpuError):
        svc.index_doc("bad2", {"jf": "comment"})

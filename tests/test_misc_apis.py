"""Sliced scroll, script_fields, rank_eval, async search, plugin SPI."""

import json
import time

import numpy as np
import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest import RestController, register_handlers


@pytest.fixture()
def env():
    node = Node()
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, params=None, raw=None):
        data = raw if raw is not None else (
            json.dumps(body).encode() if body is not None else None)
        resp = rc.dispatch(method, path, params or {}, data)
        return resp.status, json.loads(resp.encode() or b"{}")

    yield node, call
    node.close()


def fill(call, n=90):
    call("PUT", "/t", {"mappings": {"properties": {
        "body": {"type": "text"}, "n": {"type": "integer"},
        "tag": {"type": "keyword"}}}})
    for i in range(n):
        call("PUT", f"/t/_doc/{i}", {"body": f"w{i % 4} common",
                                     "n": i, "tag": f"g{i % 3}"})
    call("POST", "/t/_refresh")


def test_sliced_search_partitions_completely(env):
    node, call = env
    fill(call)
    seen = []
    for sid in range(3):
        st, r = call("POST", "/t/_search", {
            "query": {"match_all": {}}, "size": 90,
            "slice": {"id": sid, "max": 3}, "track_total_hits": True})
        assert st == 200
        ids = [h["_id"] for h in r["hits"]["hits"]]
        seen.extend(ids)
        assert 0 < len(ids) < 90          # a real split
    assert sorted(seen, key=int) == [str(i) for i in range(90)]
    # invalid slice id rejected
    st, _ = call("POST", "/t/_search", {"query": {"match_all": {}},
                                        "slice": {"id": 3, "max": 3}})
    assert st == 400


def test_script_fields(env):
    node, call = env
    fill(call, n=5)
    st, r = call("POST", "/t/_search", {
        "query": {"term": {"n": 3}},
        "script_fields": {
            "doubled": {"script": {"source": "doc['n'].value * 2"}},
            "biased": {"script": {"source": "doc['n'].value + params.b",
                                  "params": {"b": 100}}}}})
    assert st == 200
    f = r["hits"]["hits"][0]["fields"]
    assert f["doubled"] == [6.0] and f["biased"] == [103.0]


def test_rank_eval(env):
    node, call = env
    fill(call)
    body = {
        "requests": [{
            "id": "q1",
            "request": {"query": {"match": {"body": "w1"}}},
            "ratings": [{"_index": "t", "_id": "1", "rating": 1},
                        {"_index": "t", "_id": "5", "rating": 1},
                        {"_index": "t", "_id": "2", "rating": 0}],
        }],
        "metric": {"precision": {"k": 5}},
    }
    st, r = call("POST", "/t/_rank_eval", body)
    assert st == 200
    assert 0.0 < r["metric_score"] <= 1.0
    d = r["details"]["q1"]
    assert d["metric_score"] == r["metric_score"]
    assert any(h["rating"] == 1 for h in d["hits"])
    st, r = call("POST", "/t/_rank_eval", {
        "requests": body["requests"],
        "metric": {"mean_reciprocal_rank": {"k": 5}}})
    assert r["metric_score"] == 1.0      # first hit is rated relevant


def test_async_search_lifecycle(env):
    node, call = env
    fill(call)
    st, r = call("POST", "/t/_async_search",
                 {"query": {"match": {"body": "common"}},
                  "track_total_hits": True},
                 params={"wait_for_completion_timeout": "10s"})
    assert st == 200
    sid = r["id"]
    assert r["is_running"] is False and r["is_partial"] is False
    assert r["response"]["hits"]["total"]["value"] == 90
    st, r2 = call("GET", f"/_async_search/{sid}")
    assert st == 200 and r2["response"]["hits"]["total"]["value"] == 90
    st, _ = call("DELETE", f"/_async_search/{sid}")
    assert st == 200
    st, _ = call("GET", f"/_async_search/{sid}")
    assert st == 404


def test_plugin_spi(tmp_path, monkeypatch):
    import sys

    plug = tmp_path / "demo_plugin.py"
    plug.write_text(
        "def install(node, rc=None):\n"
        "    node.ingest.put_pipeline('from-plugin', {'processors': [\n"
        "        {'set': {'field': 'via', 'value': 'plugin'}}]})\n"
        "    node.plugin_touched = True\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.setenv("ES_TPU_PLUGINS", "demo_plugin")
    from elasticsearch_tpu.plugins import PluginError, load_plugins

    node = Node()
    loaded = load_plugins(node)
    assert loaded == ["demo_plugin"] and node.plugin_touched
    assert node.ingest.has("from-plugin")
    node.close()

    monkeypatch.setenv("ES_TPU_PLUGINS", "no_such_module_xyz")
    node2 = Node()
    with pytest.raises(PluginError):
        load_plugins(node2)
    node2.close()


def test_profile_and_hot_threads(env):
    node, call = env
    fill(call, n=30)
    st, r = call("POST", "/t/_search", {
        "query": {"bool": {"must": [{"match": {"body": "common"}}],
                           "filter": [{"term": {"tag": "g1"}}]}},
        "profile": True})
    assert st == 200
    prof = r["profile"]["shards"][0]["searches"][0]["query"]
    assert prof and prof[0]["type"] == "BoolQuery"
    kids = {c["type"] for c in prof[0]["children"]}
    assert {"MatchQuery", "TermQuery"} <= kids
    assert all(c["time_in_nanos"] >= 0 for c in prof[0]["children"])
    # hot_threads is text/plain — dispatch directly
    rc = RestController()
    register_handlers(node, rc)
    raw = rc.dispatch("GET", "/_nodes/hot_threads", {}, None)
    assert raw.status == 200 and b"thread [" in raw.encode()


def test_search_slow_log(env, caplog):
    import logging

    node, call = env
    call("PUT", "/slow", {"settings": {"index": {
        "search": {"slowlog": {"threshold": {"query": {"warn": "0ms"}}}}}}})
    call("PUT", "/slow/_doc/1", {"x": "hello world"})
    call("POST", "/slow/_refresh")
    with caplog.at_level(logging.WARNING, logger="index.search.slowlog"):
        call("POST", "/slow/_search", {"query": {"match": {"x": "hello"}}})
    assert any("took" in rec.message or "took" in rec.getMessage()
               for rec in caplog.records), caplog.records


def test_cluster_settings_consumers_take_effect(env):
    node, call = env
    # auto-create off -> writes to missing indices 404
    st, _ = call("PUT", "/_cluster/settings", {
        "persistent": {"action.auto_create_index": "false"}})
    assert st == 200
    st, _ = call("PUT", "/ghost/_doc/1", {"x": 1})
    assert st == 404
    st, _ = call("PUT", "/_cluster/settings", {
        "persistent": {"action.auto_create_index": "true"}})
    st, _ = call("PUT", "/ghost/_doc/1", {"x": 1})
    assert st in (200, 201)
    # atomic validation: invalid transient leaves valid persistent unapplied
    st, _ = call("PUT", "/_cluster/settings", {
        "persistent": {"search.max_buckets": 777},
        "transient": {"bogus.setting": 1}})
    assert st == 400
    st, r = call("GET", "/_cluster/settings")
    assert "search" not in r["persistent"]


def test_template_bare_topology_keys(env):
    node, call = env
    st, _ = call("PUT", "/_index_template/bare", {
        "index_patterns": ["bare-*"],
        "template": {"settings": {"number_of_shards": 2}}})
    assert st == 200
    call("PUT", "/bare-1", {})
    st, r = call("GET", "/bare-1")
    assert r["bare-1"]["settings"]["index"]["number_of_shards"] == "2"
    st, _ = call("PUT", "/_index_template/badprio", {
        "index_patterns": ["x*"], "priority": "high"})
    assert st == 400


def test_termvectors(env):
    node, call = env
    call("PUT", "/tv", {"mappings": {"properties": {"body": {"type": "text"}}}})
    call("PUT", "/tv/_doc/1", {"body": "quick brown quick fox"})
    call("POST", "/tv/_refresh")
    st, r = call("POST", "/tv/_termvectors/1", {"term_statistics": True})
    assert st == 200 and r["found"]
    terms = r["term_vectors"]["body"]["terms"]
    assert terms["quick"]["term_freq"] == 2
    assert [t["position"] for t in terms["quick"]["tokens"]] == [0, 2]
    assert terms["fox"]["doc_freq"] == 1
    st, _ = call("POST", "/tv/_termvectors/zzz", {})
    assert st == 404


def test_search_template(env):
    node, call = env
    fill(call, n=20)
    st, r = call("POST", "/_render/template", {
        "source": {"query": {"match": {"body": "{{word}}"}},
                   "size": "{{#toJson}}sz{{/toJson}}"},
        "params": {"word": "common", "sz": 3}})
    assert st == 200
    assert r["template_output"]["query"]["match"]["body"] == "common"
    assert r["template_output"]["size"] == 3
    st, r = call("POST", "/t/_search/template", {
        "source": {"query": {"match": {"body": "{{word}}"}}, "size": 5},
        "params": {"word": "common"}})
    assert st == 200 and len(r["hits"]["hits"]) == 5


def test_termvectors_realtime_and_escaping(env):
    node, call = env
    call("PUT", "/rt", {"mappings": {"properties": {"b": {"type": "text"}}}})
    call("PUT", "/rt/_doc/1", {"b": "fresh fresh words"})
    # NO refresh: termvectors must still see the doc (realtime)
    st, r = call("POST", "/rt/_termvectors/1", {})
    assert st == 200 and r["term_vectors"]["b"]["terms"]["fresh"]["term_freq"] == 2
    # template var with a quote must render safely
    fill(call, n=3)
    st, r = call("POST", "/_render/template", {
        "source": {"query": {"match": {"body": "{{w}}"}}},
        "params": {"w": 'O"Brien'}})
    assert st == 200
    assert r["template_output"]["query"]["match"]["body"] == 'O"Brien'


def test_put_index_settings_dynamic_only(env):
    node, call = env
    call("PUT", "/ps", {})
    st, _ = call("PUT", "/ps/_settings", {
        "index": {"default_pipeline": "clean-later",
                  "search": {"slowlog": {"threshold": {"query": {
                      "warn": "500ms"}}}}}})
    assert st == 200
    svc = node.indices.get("ps")
    assert svc.meta.settings.raw("index.default_pipeline") == "clean-later"
    assert svc.meta.settings.raw(
        "index.search.slowlog.threshold.query.warn") == "500ms"
    # committed THROUGH cluster state (replication/persistence path)
    cs_meta = node.cluster_state.indices["ps"]
    assert cs_meta.settings.raw("index.default_pipeline") == "clean-later"
    st, _ = call("PUT", "/ps/_settings", {"index": {"number_of_shards": 4}})
    assert st == 400
    st, _ = call("PUT", "/ps/_settings",
                 {"index": {"number_of_replicas": "three"}})
    assert st == 400
    # replica growth materializes routing entries
    st, _ = call("PUT", "/ps/_settings", {"index": {"number_of_replicas": 2}})
    assert st == 200
    routing = node.cluster_state.routing["ps"]
    assert sum(1 for r in routing if not r.primary) == 2
    st, _ = call("PUT", "/ps/_settings", {"index": {"number_of_replicas": 0}})
    routing = node.cluster_state.routing["ps"]
    assert sum(1 for r in routing if not r.primary) == 0

"""Deterministic distributed simulation of the coordination layer.

The tier-3 test strategy from the reference (ref:
AbstractCoordinatorTestCase.java): full Coordinator instances over a
DisruptableTransport on a DeterministicTaskQueue — virtual time, seeded
interleavings — with safety checked by invariants and a linearizability
checker over the replicated register.
"""

import random

import pytest

from elasticsearch_tpu.cluster.coordination import (
    Coordinator, PublishedState,
)
from elasticsearch_tpu.testing.deterministic import DeterministicTaskQueue
from elasticsearch_tpu.testing.disruptable_transport import DisruptableTransport
from elasticsearch_tpu.testing.linearizability import (
    CasRegisterSpec, Event, LinearizabilityChecker,
)


class SimCluster:
    def __init__(self, node_ids, seed=0):
        self.queue = DeterministicTaskQueue(seed)
        self.transport = DisruptableTransport(self.queue)
        config = frozenset(node_ids)
        initial = PublishedState(term=0, version=0, value=None,
                                 config=config, last_committed_config=config)
        self.nodes = {}
        self.committed = {n: [] for n in node_ids}
        for n in node_ids:
            rng = random.Random(hash((seed, n)) & 0xFFFF)
            node = Coordinator(
                n, initial, self.transport, self.queue, rng,
                on_commit=lambda st, n=n: self.committed[n].append(st))
            self.nodes[n] = node
            self.transport.register(n, node.handle_message)

    def start(self):
        for n in self.nodes.values():
            n.start()

    def run(self, ms):
        self.queue.run_until(self.queue.now_ms + ms)

    def leaders(self):
        return [n for n in self.nodes.values() if n.mode == "LEADER"]

    def stable_leader(self):
        ls = self.leaders()
        assert len(ls) == 1, f"expected one leader, got {[l.node_id for l in ls]}"
        return ls[0]


def test_single_node_elects_itself():
    c = SimCluster(["n0"])
    c.start()
    c.run(5_000)
    leader = c.stable_leader()
    assert leader.node_id == "n0"
    assert c.committed["n0"]   # the no-op republish committed


@pytest.mark.parametrize("seed", range(5))
def test_three_nodes_elect_exactly_one_leader(seed):
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(30_000)
    leader = c.stable_leader()
    # everyone else follows that leader
    for n in c.nodes.values():
        if n is not leader:
            assert n.mode == "FOLLOWER"
            assert n.leader_id == leader.node_id


@pytest.mark.parametrize("seed", range(3))
def test_publish_reaches_all_nodes(seed):
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(30_000)
    leader = c.stable_leader()
    leader.publish({"doc": 42})
    c.run(5_000)
    for n, states in c.committed.items():
        assert states, f"{n} committed nothing"
        assert states[-1].value == {"doc": 42}


@pytest.mark.parametrize("seed", range(3))
def test_leader_loss_triggers_reelection(seed):
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(30_000)
    old = c.stable_leader()
    c.transport.isolate(old.node_id)
    c.run(60_000)
    remaining = [n for n in c.nodes.values()
                 if n.node_id != old.node_id and n.mode == "LEADER"]
    assert len(remaining) == 1
    new_leader = remaining[0]
    assert new_leader.state.current_term > old.state.current_term
    # the isolated old leader cannot commit anything new
    before = len(c.committed[old.node_id])
    try:
        old.publish({"stale": True})
    except Exception:
        pass
    c.run(10_000)
    stale_commits = c.committed[old.node_id][before:]
    assert all(s.value != {"stale": True} for s in stale_commits)


@pytest.mark.parametrize("seed", range(3))
def test_minority_partition_cannot_commit(seed):
    c = SimCluster(["n0", "n1", "n2", "n4", "n5"], seed=seed)
    c.start()
    c.run(30_000)
    leader = c.stable_leader()
    others = [n for n in c.nodes if n != leader.node_id]
    minority = {leader.node_id, others[0]}
    majority = set(others[1:])
    c.transport.partition(minority, majority)
    # leader in minority: publishes must not commit anywhere
    n_before = {n: len(c.committed[n]) for n in c.nodes}
    try:
        leader.publish({"lost": True})
    except Exception:
        pass
    c.run(60_000)
    for n in majority:
        vals = [s.value for s in c.committed[n][n_before[n]:]]
        assert {"lost": True} not in vals
    # majority side elects a fresh leader and can commit
    maj_leaders = [c.nodes[n] for n in majority if c.nodes[n].mode == "LEADER"]
    assert len(maj_leaders) == 1
    maj_leaders[0].publish({"fresh": True})
    c.run(10_000)
    for n in majority:
        assert c.committed[n][-1].value == {"fresh": True}


@pytest.mark.parametrize("seed", range(3))
def test_partition_heals_and_converges(seed):
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(30_000)
    leader = c.stable_leader()
    c.transport.isolate(leader.node_id)
    c.run(60_000)
    c.transport.heal()
    c.run(60_000)
    ls = c.leaders()
    assert len(ls) == 1
    ls[0].publish({"converged": True})
    c.run(10_000)
    versions = {c.committed[n][-1].version for n in c.nodes if c.committed[n]}
    values = [c.committed[n][-1].value for n in c.nodes if c.committed[n]]
    assert all(v == {"converged": True} for v in values)
    assert len(versions) == 1


@pytest.mark.parametrize("seed", range(8))
def test_committed_states_form_single_history(seed):
    """Safety invariant: across all nodes, committed (term, version) -> value
    is a function, and versions on each node are monotonic."""
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(20_000)
    # publish from whoever leads, with disruptions between rounds
    for round_ in range(3):
        ls = c.leaders()
        if len(ls) == 1:
            try:
                ls[0].publish({"round": round_, "seed": seed})
            except Exception:
                pass
        if round_ == 1:
            victim = list(c.nodes)[seed % 3]
            c.transport.isolate(victim)
            c.run(20_000)
            c.transport.heal()
        c.run(20_000)
    seen = {}
    for n, states in c.committed.items():
        versions = [s.version for s in states]
        assert versions == sorted(versions), f"{n} saw non-monotonic versions"
        for s in states:
            key = (s.term, s.version)
            if key in seen:
                assert seen[key] == s.value, (
                    f"divergent committed value at {key}")
            else:
                seen[key] = s.value


def test_linearizability_checker_accepts_valid_history():
    checker = LinearizabilityChecker(CasRegisterSpec())
    # w0: cas(0->A) ok; concurrent w1: cas(0->B) fails; read sees (1, A)
    history = [
        Event("invoke", 0, ("write", (0, "A"))),
        Event("invoke", 1, ("write", (0, "B"))),
        Event("response", 0, True),
        Event("response", 1, False),
        Event("invoke", 2, ("read", None)),
        Event("response", 2, (1, "A")),
    ]
    assert checker.is_linearizable(history)


def test_linearizability_checker_rejects_divergence():
    checker = LinearizabilityChecker(CasRegisterSpec())
    # both CAS(0->X) claims succeeded: impossible for one register
    history = [
        Event("invoke", 0, ("write", (0, "A"))),
        Event("invoke", 1, ("write", (0, "B"))),
        Event("response", 0, True),
        Event("response", 1, True),
    ]
    assert not checker.is_linearizable(history)


def test_linearizability_checker_rejects_stale_read_after_ack():
    checker = LinearizabilityChecker(CasRegisterSpec())
    # write committed and acknowledged BEFORE the read was invoked, but the
    # read still saw the initial state: a real-time violation
    history = [
        Event("invoke", 0, ("write", (0, "A"))),
        Event("response", 0, True),
        Event("invoke", 1, ("read", None)),
        Event("response", 1, (0, None)),
    ]
    assert not checker.is_linearizable(history)


def test_linearizability_checker_incomplete_ops_optional():
    checker = LinearizabilityChecker(CasRegisterSpec())
    # a write with no response may or may not have happened: both read
    # outcomes are linearizable
    for observed in [(0, None), (1, "A")]:
        history = [
            Event("invoke", 0, ("write", (0, "A"))),
            Event("invoke", 1, ("read", None)),
            Event("response", 1, observed),
        ]
        assert checker.is_linearizable(history), observed


@pytest.mark.parametrize("seed", range(4))
def test_acknowledged_writes_survive_in_order(seed):
    """State-machine-replication witness: every write acknowledged by commit
    appears in every node's committed history, and in the same relative
    order everywhere — across leader churn and partitions."""
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(30_000)
    acked = []
    for i in range(4):
        ls = c.leaders()
        if len(ls) == 1:
            value = {"w": i, "seed": seed}
            try:
                ls[0].publish(value)
            except Exception:
                value = None
            c.run(15_000)
            if value is not None and any(
                    s.value == value for s in c.committed[ls[0].node_id]):
                acked.append(value)
        if i == 1:
            victim = list(c.nodes)[(seed + 1) % 3]
            c.transport.isolate(victim)
            c.run(40_000)
            c.transport.heal()
        c.run(20_000)
    c.run(60_000)
    assert acked, "no write was ever acknowledged"
    for n, states in c.committed.items():
        vals = [s.value for s in states]
        positions = [vals.index(a) for a in acked if a in vals]
        # all acked writes present on every healed node...
        missing = [a for a in acked if a not in vals]
        assert not missing, f"{n} lost acknowledged writes {missing}"
        # ...and in the same order they were acknowledged
        assert positions == sorted(positions), f"{n} reordered writes"


@pytest.mark.parametrize("seed", range(4))
def test_isolated_node_catches_up_after_heal(seed):
    """A write committed by the majority DURING the partition must reach the
    isolated node after healing (lag detection + catch-up publish)."""
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(30_000)
    leader = c.stable_leader()
    victim = next(n for n in c.nodes.values() if n is not leader)
    c.transport.isolate(victim.node_id)
    c.run(20_000)
    value = {"while_partitioned": True, "seed": seed}
    leader.publish(value)
    c.run(15_000)
    # majority committed it; victim did not
    assert any(s.value == value for s in c.committed[leader.node_id])
    assert not any(s.value == value for s in c.committed[victim.node_id])
    c.transport.heal()
    c.run(60_000)
    assert any(s.value == value for s in c.committed[victim.node_id]), \
        "victim never caught up"


@pytest.mark.parametrize("seed", range(3))
def test_follower_that_missed_only_the_commit_catches_up(seed):
    """A follower that ACCEPTS a publish but never sees its commit must
    converge: the leader's catch-up re-publish of the same (term, version) is
    re-acked idempotently so the commit gets re-sent."""
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(30_000)
    leader = c.stable_leader()
    victim = next(n for n in c.nodes if n != leader.node_id)
    orig_send = c.transport.send
    dropped = []

    def send(sender, to, msg, on_reply, on_error=None):
        if msg.get("type") == "commit" and to == victim:
            dropped.append(msg)
            return
        orig_send(sender, to, msg, on_reply, on_error)

    c.transport.send = send
    value = {"missed_commit": True, "seed": seed}
    leader.publish(value)
    c.run(2_000)   # publish accepted everywhere; victim's commit swallowed
    c.transport.send = orig_send
    assert dropped, "test setup: no commit was dropped"
    assert not any(s.value == value for s in c.committed[victim])
    c.run(60_000)  # follower checks spot the lag and re-publish + commit
    assert any(s.value == value for s in c.committed[victim]), \
        "victim stuck: accepted state never committed"


@pytest.mark.parametrize("seed", range(3))
def test_isolated_leader_cannot_shrink_config_to_itself(seed):
    """Regression: an isolated leader's failed-follower reconfigurations must
    never commit (joint consensus anchors on the last COMMITTED config), and
    the leader must step down after the publication timeout."""
    c = SimCluster(["n0", "n1", "n2", "n3", "n4"], seed=seed)
    c.start()
    c.run(30_000)
    leader = c.stable_leader()
    c.transport.isolate(leader.node_id)
    c.run(300_000)   # long isolation: follower checks fail, shrinks attempted
    # nothing committed on the isolated node beyond what it had
    for s in c.committed[leader.node_id]:
        assert len(s.config) >= 3, f"committed dangerously small config {s.config}"
    # publication timeout forced it out of LEADER mode
    assert leader.mode != "LEADER"
    # majority side is healthy with a proper config
    maj = [n for n in c.nodes.values()
           if n.node_id != leader.node_id and n.mode == "LEADER"]
    assert len(maj) == 1


@pytest.mark.parametrize("seed", range(3))
def test_sequential_leader_failures_with_autoshrink(seed):
    """5 nodes, kill 3 successive leaders: auto-reconfiguration keeps the
    shrinking remainder quorate (static config would die at the 3rd kill)."""
    c = SimCluster(["n0", "n1", "n2", "n3", "n4"], seed=seed)
    c.start()
    c.run(30_000)
    isolated = set()
    for round_ in range(3):
        ls = [n for n in c.nodes.values()
              if n.mode == "LEADER" and n.node_id not in isolated]
        assert len(ls) == 1, f"round {round_}"
        ls[0].publish({"round": round_})
        c.run(10_000)
        c.transport.isolate(ls[0].node_id)
        isolated.add(ls[0].node_id)
        c.run(90_000)
    alive_leaders = [n for n in c.nodes.values()
                     if n.node_id not in isolated and n.mode == "LEADER"]
    assert len(alive_leaders) == 1
    alive_leaders[0].publish({"survived": True})
    c.run(10_000)
    for n in c.nodes.values():
        if n.node_id not in isolated:
            assert c.committed[n.node_id][-1].value == {"survived": True}


@pytest.mark.parametrize("seed", range(3))
def test_removed_node_rejoins_after_heal(seed):
    c = SimCluster(["n0", "n1", "n2"], seed=seed)
    c.start()
    c.run(30_000)
    leader = c.stable_leader()
    victim = next(n for n in c.nodes.values() if n is not leader)
    c.transport.isolate(victim.node_id)
    c.run(120_000)   # leader shrinks config, removing the victim
    ls = [n for n in c.nodes.values() if n.mode == "LEADER"]
    assert len(ls) == 1
    assert victim.node_id not in ls[0].state.accepted.config
    c.transport.heal()
    c.run(120_000)   # victim discovers the leader and asks to rejoin
    ls = [n for n in c.nodes.values() if n.mode == "LEADER"]
    assert len(ls) == 1
    assert victim.node_id in ls[0].state.accepted.config
    assert victim.mode == "FOLLOWER"

import os

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import VersionConflictError
from elasticsearch_tpu.index.engine import InternalEngine
from elasticsearch_tpu.index.seqno import LocalCheckpointTracker, ReplicationTracker
from elasticsearch_tpu.index.translog import Translog
from elasticsearch_tpu.mapper import MapperService

MAPPING = {"properties": {"body": {"type": "text"}, "n": {"type": "long"}}}


def make_engine(path=None):
    return InternalEngine(MapperService(dict(MAPPING)), data_path=path)


def test_index_get_update_delete_lifecycle():
    e = make_engine()
    r = e.index("1", {"body": "hello world", "n": 1})
    assert (r.result, r.version, r.seq_no) == ("created", 1, 0)
    got = e.get("1")
    assert got["_source"]["n"] == 1 and got["_version"] == 1
    r2 = e.index("1", {"body": "hello again", "n": 2})
    assert (r2.result, r2.version) == ("updated", 2)
    assert e.get("1")["_source"]["n"] == 2
    r3 = e.delete("1")
    assert (r3.result, r3.version) == ("deleted", 3)
    assert e.get("1") is None
    assert e.delete("1").result == "not_found"


def test_realtime_get_before_refresh_and_searchable_after():
    e = make_engine()
    e.index("a", {"body": "x"})
    assert e.get("a") is not None          # realtime from buffer
    searcher = e.acquire_searcher()
    assert searcher.n_docs == 0            # not yet refreshed
    assert e.refresh() is True
    assert e.acquire_searcher().n_docs == 1
    assert e.refresh() is False            # nothing new


def test_update_across_segments_tombstones_old_copy():
    e = make_engine()
    e.index("a", {"body": "v1"})
    e.index("b", {"body": "other"})
    e.refresh()
    e.index("a", {"body": "v2"})
    e.refresh()
    s = e.acquire_searcher()
    assert len(s.views) == 2
    assert s.n_docs == 2                   # old copy of a is dead
    assert not s.views[0].live[0]          # a's first copy tombstoned
    assert e.doc_count() == 2


def test_optimistic_concurrency():
    e = make_engine()
    r = e.index("a", {"body": "x"})
    with pytest.raises(VersionConflictError):
        e.index("a", {"body": "y"}, if_seq_no=99, if_primary_term=1)
    e.index("a", {"body": "y"}, if_seq_no=r.seq_no, if_primary_term=1)
    with pytest.raises(VersionConflictError):
        e.index("a", {"body": "z"}, op_type="create")
    with pytest.raises(VersionConflictError):
        e.delete("a", if_seq_no=0, if_primary_term=1)  # seq advanced to 1


def test_delete_in_buffer_doc():
    e = make_engine()
    e.index("a", {"body": "x"})
    e.delete("a")
    e.refresh()
    assert e.acquire_searcher().n_docs == 0
    assert e.doc_count() == 0


def test_force_merge_compacts_and_preserves():
    e = make_engine()
    for i in range(10):
        e.index(str(i), {"body": f"doc {i}", "n": i})
        if i % 3 == 0:
            e.refresh()
    e.delete("4")
    e.index("5", {"body": "updated five", "n": 50})
    e.force_merge()
    assert e.segment_count() == 1
    assert e.doc_count() == 9
    assert e.get("5")["_source"]["n"] == 50
    assert e.get("5")["_version"] == 2
    assert e.get("4") is None


def test_translog_replay_after_crash(tmp_path):
    path = str(tmp_path / "shard0")
    e = make_engine(path)
    e.index("1", {"body": "one", "n": 1})
    e.index("2", {"body": "two", "n": 2})
    e.delete("1")
    # no flush — simulate crash; reopen
    e.close()
    e2 = make_engine(path)
    assert e2.get("1") is None
    assert e2.get("2")["_source"]["n"] == 2
    assert e2.max_seq_no == 2
    assert e2.local_checkpoint == 2
    e2.close()


def test_flush_commit_and_recover_with_tail(tmp_path):
    path = str(tmp_path / "shard0")
    e = make_engine(path)
    for i in range(5):
        e.index(str(i), {"body": f"doc {i}", "n": i})
    e.flush()
    e.index("5", {"body": "after commit", "n": 5})
    e.index("0", {"body": "updated zero", "n": 100})
    e.close()

    e2 = make_engine(path)
    assert e2.doc_count() == 6
    assert e2.get("5")["_source"]["n"] == 5
    assert e2.get("0")["_source"]["n"] == 100
    assert e2.get("0")["_version"] == 2
    assert e2.local_checkpoint == 6
    # translog generations below commit were trimmed
    assert len(e2.translog.generations()) <= 2
    e2.close()


def test_flush_idempotent_and_live_masks_persisted(tmp_path):
    path = str(tmp_path / "s")
    e = make_engine(path)
    e.index("a", {"body": "x"})
    e.index("b", {"body": "y"})
    e.flush()
    e.delete("a")
    e.flush()
    e.close()
    e2 = make_engine(path)
    assert e2.doc_count() == 1
    assert e2.get("a") is None and e2.get("b") is not None
    e2.close()


def test_translog_torn_tail_tolerated(tmp_path):
    t = Translog(str(tmp_path / "tl"))
    t.add({"op": "index", "id": "1", "seq_no": 0, "source": {}})
    t.add({"op": "index", "id": "2", "seq_no": 1, "source": {}})
    t.close()
    # append garbage partial record
    files = [f for f in os.listdir(tmp_path / "tl")]
    with open(tmp_path / "tl" / files[0], "ab") as f:
        f.write(b"\x50\x00\x00\x00\x12\x34")
    t2 = Translog(str(tmp_path / "tl"))
    ops = list(t2.read_ops())
    assert [o["id"] for o in ops] == ["1", "2"]
    t2.close()


def test_local_checkpoint_tracker_gaps():
    t = LocalCheckpointTracker()
    s0, s1, s2 = t.generate_seq_no(), t.generate_seq_no(), t.generate_seq_no()
    t.mark_processed(s2)
    assert t.checkpoint == -1
    t.mark_processed(s0)
    assert t.checkpoint == 0
    t.mark_processed(s1)
    assert t.checkpoint == 2
    assert t.max_seq_no == 2


def test_replication_tracker_global_checkpoint():
    rt = ReplicationTracker("p")
    rt.update_local_checkpoint("p", 5)
    assert rt.global_checkpoint == 5
    rt.mark_in_sync("r1")
    rt.update_local_checkpoint("r1", 3)
    # min over in-sync set, but never backwards
    assert rt.global_checkpoint == 5
    rt.update_local_checkpoint("r1", 7)
    rt.update_local_checkpoint("p", 9)
    assert rt.global_checkpoint == 7
    rt.remove_tracking("r1")
    assert rt.global_checkpoint == 9


def test_segment_payloads_install_roundtrip(tmp_path):
    """File-phase recovery transfer: payloads from one engine install into
    an empty one with identical docs, deletes, and seqno state."""
    src = make_engine()
    for i in range(20):
        src.index(str(i), {"n": i, "body": f"doc {i}"})
    src.delete("3")
    src.refresh()
    src.index("5", {"n": 55, "body": "updated five"})  # cross-segment update
    payloads, max_seq = src.segment_payloads()
    assert max_seq == src.max_seq_no

    dst = make_engine(str(tmp_path / "dst"))
    for blob, live in payloads:
        dst.install_segment(blob, live)
    dst.fill_seqno_gaps(max_seq)
    assert dst.doc_count() == src.doc_count() == 19
    assert dst.get("3") is None
    assert dst.get("5")["_source"] == {"n": 55, "body": "updated five"}
    assert dst.local_checkpoint == max_seq

    # installed segments got LOCAL seg ids: flush + crash-recover stays sane
    dst.flush()
    dst.close()
    recovered = make_engine(str(tmp_path / "dst"))
    assert recovered.doc_count() == 19
    assert recovered.get("5")["_source"]["n"] == 55


def test_install_segment_remaps_colliding_seg_ids(tmp_path):
    """A locally-refreshed segment and an installed one must never share a
    seg id, or flush()'s dedup-by-filename corrupts the commit."""
    src = make_engine()
    src.index("a", {"n": 1, "body": "one"})
    src.refresh()
    payloads, max_seq = src.segment_payloads()

    dst = make_engine(str(tmp_path / "dst"))
    # local write + refresh first: local segment takes seg_id 0
    dst.index("b", {"n": 2, "body": "two"}, seq_no=99)
    dst.refresh()
    for blob, live in payloads:
        dst.install_segment(blob, live)
    ids = [s.seg_id for s in dst._segments]
    assert len(ids) == len(set(ids)), f"colliding seg ids {ids}"
    dst.flush()
    dst.close()
    recovered = make_engine(str(tmp_path / "dst"))
    assert recovered.doc_count() == 2
    assert recovered.get("a") is not None and recovered.get("b") is not None


def test_install_segment_racing_live_write_wins():
    """A replicated write that raced ahead of the phase1 transfer must not
    be clobbered by the installed (older) copy of the same doc."""
    src = make_engine()
    src.index("x", {"n": 1, "body": "old"})
    src.refresh()
    payloads, _ = src.segment_payloads()

    dst = make_engine()
    dst.index("x", {"n": 2, "body": "new"}, seq_no=7)  # live op, higher seqno
    for blob, live in payloads:
        dst.install_segment(blob, live)
    assert dst.get("x")["_source"]["n"] == 2
    dst.refresh()
    assert dst.doc_count() == 1

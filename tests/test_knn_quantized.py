"""Quantized sharded kNN differential suite (PR 19).

The KnnEngine first pass scores per-row int8 quantized vectors with the
`knn_int8_window_topc` Pallas kernel, carrying a tracked quantization
bound so the candidate set is a provable SUPERSET of the true top-k;
survivors are exact-rescored on device (bf16 gemm, same arithmetic as
the `knn_top_k` f32 reference) and merged with the deterministic
(score desc, partition asc, doc asc) tie-break. The contract: top-k is
BIT-identical to the f32 brute-force reference on every route — solo,
fused S > 1 over the ICI mesh, filtered, the `ES_TPU_KNN_INT8=0` dense
A/B, and IVF at nprobe=0. IVF coarse pruning trades exactness for
probes: recall@10 must stay >= 0.99 at the documented probe count.

Fault plane: an injected `knn_score` fault on one partition is contained
to that partition (peers still serve from device, the failed partition
falls back to the exact host path); an `hbm_region` flip on the int8
shard pool is detected by the scrubber, repaired from the host mirror,
and the repaired engine answers bit-identically.

Runs on the host-simulated 8-device CPU mesh from tests/conftest.py
(Pallas kernels interpret on CPU)."""

import numpy as np
import pytest

from elasticsearch_tpu.common import faults, integrity
from elasticsearch_tpu.index.segment import VectorColumn
from elasticsearch_tpu.parallel import knn as knn_mod
from elasticsearch_tpu.parallel.knn import KnnEngine, KnnWork
from elasticsearch_tpu.parallel.spmd import make_mesh

pytestmark = pytest.mark.multidevice

K = 10
DIMS = 48


def _cols(sizes, dims=DIMS, similarity="cosine", seed=7, unit=False):
    rng = np.random.default_rng(seed)
    cols = []
    for n in sizes:
        v = rng.standard_normal((n, dims)).astype(np.float32)
        if unit:
            v /= np.maximum(np.linalg.norm(v, axis=1), 1e-20)[:, None]
        cols.append(VectorColumn(
            vectors=v, norms=np.linalg.norm(v, axis=1).astype(np.float32),
            exists=rng.random(n) > 0.04, dims=dims, similarity=similarity))
    return cols


def _queries(nq, dims=DIMS, seed=3, unit=False):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((nq, dims)).astype(np.float32)
    if unit:
        q /= np.maximum(np.linalg.norm(q, axis=1), 1e-20)[:, None]
    return q


def _reference(cols, qs, k, similarity, masks=None):
    """f32 brute force: `knn_top_k` per partition (rows pre-normalized
    for cosine, exactly as the engine stores them) + the deterministic
    (score desc, partition asc, ord asc) merge. s <= 0 marks empty."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ops.knn import knn_top_k

    nq = len(qs)
    per = []
    for pi, col in enumerate(cols):
        v = col.vectors
        if similarity == "cosine":
            v = v / np.maximum(col.norms, 1e-20)[:, None]
        mask = np.ones(len(v), bool) if masks is None else masks[pi]
        ts, to, ok = knn_top_k(
            jnp.asarray(qs), jnp.asarray(v).astype(jnp.bfloat16),
            jnp.asarray(col.norms), jnp.asarray(col.exists),
            jnp.asarray(mask), similarity=similarity, k=k)
        ts, to = np.asarray(ts), np.asarray(to)
        per.append((np.where(np.asarray(ok), ts, 0.0), to))
    ws = np.zeros((nq, k), np.float32)
    wp = np.zeros((nq, k), np.int32)
    wo = np.zeros((nq, k), np.int32)
    for qi in range(nq):
        rows = [(rs[qi, j], pi, ro[qi, j])
                for pi, (rs, ro) in enumerate(per)
                for j in range(k) if rs[qi, j] > 0]
        rows.sort(key=lambda r: (-r[0], r[1], r[2]))
        for j, (sv, pv, ov) in enumerate(rows[:k]):
            ws[qi, j], wp[qi, j], wo[qi, j] = sv, pv, ov
    return ws, wp, wo


def _assert_identical(got, want, label):
    gs, gp, go = got
    ws, wp, wo = want
    assert np.array_equal(np.asarray(gs), ws), f"{label}: scores differ"
    assert np.array_equal(np.asarray(gp), wp), f"{label}: partitions differ"
    assert np.array_equal(np.asarray(go), wo), f"{label}: ords differ"


@pytest.mark.parametrize("similarity", ["cosine", "dot_product", "l2_norm"])
def test_int8_solo_bit_identical(similarity):
    unit = similarity == "dot_product"      # ES contract: unit vectors
    cols = _cols([3000], similarity=similarity, unit=unit)
    qs = _queries(20, unit=unit)
    eng = KnnEngine(cols)
    knn_mod.reset_for_tests()
    got = eng.search_many([[KnnWork(q) for q in qs]], k=K)[0]
    want = _reference(cols, qs, K, similarity)
    _assert_identical(got, want, f"solo {similarity}")
    st = knn_mod.knn_node_stats()
    assert st["knn_int8_dispatches"] > 0, "int8 route never engaged"
    assert st["knn_host_fallbacks"] == 0
    assert st["knn_rescore_docs"] > 0


def test_int8_fused_sharded_bit_identical():
    """S=3 over a 4-way ICI mesh, query count straddling two qc rungs."""
    cols = _cols([2500, 1800, 2100], seed=17)
    qs = _queries(40, seed=5)
    eng = KnnEngine(cols, mesh=make_mesh(4, dp=1))
    assert eng._fused, "mesh engine did not take the fused route"
    got = eng.search_many([[KnnWork(q) for q in qs]], k=K)[0]
    _assert_identical(got, _reference(cols, qs, K, "cosine"), "fused S=3")


def test_int8_off_ab_identical(monkeypatch):
    """ES_TPU_KNN_INT8=0 serves the same bits through the dense f32
    route with zero int8 dispatches."""
    cols = _cols([2200, 1600], seed=23)
    qs = _queries(16, seed=9)
    on = KnnEngine(cols)
    got_on = on.search_many([[KnnWork(q) for q in qs]], k=K)[0]
    monkeypatch.setenv("ES_TPU_KNN_INT8", "0")
    knn_mod.reset_for_tests()
    off = KnnEngine(cols)
    got_off = off.search_many([[KnnWork(q) for q in qs]], k=K)[0]
    _assert_identical(got_on, got_off, "int8 on vs off A/B")
    _assert_identical(got_off, _reference(cols, qs, K, "cosine"),
                      "int8 off vs reference")
    st = knn_mod.knn_node_stats()
    assert st["knn_int8_dispatches"] == 0, "int8 dispatched despite knob"
    assert st["knn_queries"] > 0


def test_filtered_bit_identical():
    """Per-partition filter masks (the BM25 candidate mask shape used by
    hybrid fusion) constrain the int8 pass and the reference equally."""
    cols = _cols([2400, 1900], seed=29)
    qs = _queries(12, seed=13)
    rng = np.random.default_rng(41)
    masks = [rng.random(len(c.vectors)) > 0.6 for c in cols]
    eng = KnnEngine(cols)
    works = [KnnWork(q, filters=masks) for q in qs]
    got = eng.search_many([works], k=K)[0]
    _assert_identical(got, _reference(cols, qs, K, "cosine", masks=masks),
                      "filtered")


def test_ivf_nprobe_zero_exact_and_recall(monkeypatch):
    """IVF builds at n >= 4096: nprobe=0 stays bit-exact; at the
    documented probe count recall@10 >= 0.99."""
    cols = _cols([9000], seed=37)
    qs = _queries(32, seed=19)
    eng = KnnEngine(cols)
    assert eng._cent_host[0].shape[0] > 1, "IVF never built at n=9000"
    want = _reference(cols, qs, K, "cosine")
    _assert_identical(eng.search_many([[KnnWork(q) for q in qs]], k=K)[0],
                      want, "ivf nprobe=0")

    monkeypatch.setenv("ES_TPU_KNN_NPROBE", "24")
    got = eng.search_many([[KnnWork(q) for q in qs]], k=K)[0]
    hits = total = 0
    for qi in range(len(qs)):
        truth = {(p, o) for s, p, o in
                 zip(want[0][qi], want[1][qi], want[2][qi]) if s > 0}
        found = {(p, o) for s, p, o in
                 zip(np.asarray(got[0])[qi], np.asarray(got[1])[qi],
                     np.asarray(got[2])[qi]) if s > 0}
        hits += len(truth & found)
        total += len(truth)
    assert total > 0 and hits / total >= 0.99, \
        f"IVF recall@10 {hits / total:.4f} < 0.99 at nprobe=24"


@pytest.mark.faults
def test_knn_score_fault_contained_per_partition():
    """An injected knn_score fault on partition 1 is contained: the
    fault log names only partition 1, peers keep serving, and the host
    fallback stays correctness-equal to the exact reference."""
    cols = _cols([1500, 1200, 1400], seed=43)
    qs = _queries(8, seed=21)
    eng = KnnEngine(cols)          # solo route: per-partition dispatch
    works = [[KnnWork(q) for q in qs]]
    want = _reference(cols, qs, K, "cosine")
    knn_mod.reset_for_tests()
    flog = []
    with faults.inject("knn_score#1:raise@1"):
        s, p, o = eng.search_many(works, k=K, fault_log=flog)[0]
    assert flog, "fault not surfaced in the fault log"
    assert all(r.partition == 1 for r in flog), \
        f"fault leaked beyond partition 1: {flog}"
    assert all(r.site == "knn_score" and r.recovered for r in flog)
    s, p, o = np.asarray(s), np.asarray(p), np.asarray(o)
    ws, wp, wo = want
    # host fallback is exact-f64 while the reference rounds rows to
    # bf16: correctness-equal to bf16 row precision, not bitwise
    assert np.allclose(s, ws, rtol=5e-3, atol=5e-3)
    overlap = np.mean([
        len({(a, b) for a, b in zip(p[i], o[i])}
            & {(a, b) for a, b in zip(wp[i], wo[i])}) / K
        for i in range(len(qs))])
    assert overlap >= 0.95, f"top-{K} overlap {overlap:.3f} after fault"
    # untouched partitions still answered on device
    eng2 = KnnEngine(cols)
    _assert_identical(eng2.search_many(works, k=K)[0], want,
                      "engine after clean rebuild")


@pytest.mark.faults
def test_knn_scrub_bitflip_repair():
    """An injected hbm_region flip on the int8 shard pool is detected by
    the scrubber, repaired from the host mirror, and the repaired engine
    answers bit-identically."""
    cols = _cols([1800, 1300], seed=47)
    qs = _queries(10, seed=25)
    works = [[KnnWork(q) for q in qs]]
    want = _reference(cols, qs, K, "cosine")

    integrity.reset_scrub_for_tests()      # only the engine below scrubs
    eng = KnnEngine(cols)
    _assert_identical(eng.search_many(works, k=K)[0], want, "pre-flip")

    def cycle():
        return [integrity.scrub_once()
                for _ in range(integrity.scrub_registry_size())]

    cycle()                                # baseline pass: all clean
    m0 = integrity.integrity_stats()["scrub_mismatches"]
    with faults.inject("hbm_region#knn_shards:raise@1x1"):
        results = cycle()
    hit = [r for r in results if r and r["result"] == "mismatch"]
    assert len(hit) == 1 and hit[0]["region"].endswith(".knn_shards")
    st = integrity.integrity_stats()
    assert st["scrub_mismatches"] == m0 + 1
    assert st["scrub_repairs"] >= 1
    _assert_identical(eng.search_many(works, k=K)[0], want,
                      "repaired engine vs reference")
    cycle()                                # repair re-baselined the region
    assert integrity.integrity_stats()["scrub_mismatches"] == m0 + 1


def test_ledger_matches_engine_bytes():
    cols = _cols([2000, 1500], seed=53)
    eng = KnnEngine(cols, mesh=make_mesh(2, dp=1))
    eng.search_many([[KnnWork(q) for q in _queries(4)]], k=K)
    assert eng._hbm.total_bytes() == eng.hbm_bytes()
    st = eng.stats()
    assert st["hbm_bytes"] == eng.hbm_bytes()
    assert st["partitions"] == 2 and st["fused"]
    node = knn_mod.knn_node_stats()
    assert node["engines"] >= 1
    assert node["hbm_bytes"] >= eng.hbm_bytes()


class TestServingFastPath:
    """REST-level knn bodies through IndexService: the quantized fast
    path (forced eligible via ES_TPU_FORCE_KNN) must match _search_dense
    — ids exactly, scores to f32 tolerance."""

    @pytest.fixture()
    def svc(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_FORCE_KNN", "1")
        from elasticsearch_tpu.cluster.state import IndexMetadata
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.index_service import IndexService

        meta = IndexMetadata(
            index="t", uuid="u1", settings=Settings({}),
            mappings={"properties": {
                "body": {"type": "text"},
                "tag": {"type": "keyword"},
                "vec": {"type": "dense_vector", "dims": 8},
            }})
        svc = IndexService(meta)
        rng = np.random.default_rng(59)
        words = ["alpha", "beta", "gamma", "delta"]
        for i in range(220):
            svc.index_doc(str(i), {
                "body": " ".join(rng.choice(words, size=4)),
                "tag": str(rng.choice(["red", "green"])),
                "vec": [float(x) for x in rng.standard_normal(8)],
            })
        for i in range(0, 40, 9):
            svc.delete_doc(str(i))
        svc.refresh()
        yield svc
        svc.close()

    def _check(self, svc, body):
        fast = svc.serving.try_search(body, "query_then_fetch")
        assert fast is not None, f"knn fast path did not engage: {body}"
        dense = svc._search_dense(body)
        fh, dh = fast["hits"]["hits"], dense["hits"]["hits"]
        assert [h["_id"] for h in fh] == [h["_id"] for h in dh], body
        for a, b in zip(fh, dh):
            assert abs(a["_score"] - b["_score"]) <= \
                2e-4 * abs(b["_score"]) + 2e-4, body

    def test_knn_bodies_match_dense(self, svc):
        qv = [float(x) for x in np.random.default_rng(61).standard_normal(8)]
        for body in [
            {"knn": {"field": "vec", "query_vector": qv, "k": 7}},
            {"knn": {"field": "vec", "query_vector": qv, "k": 12,
                     "filter": {"term": {"tag": "red"}}}, "size": 12},
            {"knn": {"field": "vec", "query_vector": qv, "k": 9,
                     "filter": {"bool": {
                         "must": [{"term": {"tag": "green"}}],
                         "must_not": [{"term": {"body": "alpha"}}]}}}},
        ]:
            self._check(svc, body)

    def test_hybrid_query_plus_knn_stays_dense(self, svc):
        qv = [0.5] * 8
        body = {"query": {"match": {"body": "alpha"}},
                "knn": {"field": "vec", "query_vector": qv, "k": 5}}
        assert svc.serving.try_search(body, "query_then_fetch") is None
        assert svc._search_dense(body) is not None

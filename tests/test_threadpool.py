"""Named bounded executors + TPU dispatch coalescer (threadpool/).

Admission control: saturating one named pool rejects with 429
`es_rejected_execution_exception` (pool name in the reason) without
affecting the other pools. Coalescing: concurrent single-query searches
on the same engine merge into ONE device dispatch whose de-multiplexed
rows are BIT-identical to solo execution — across turbo and blockmax
engines, and under a mid-window snapshot refresh (engine swap).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from elasticsearch_tpu.threadpool import (
    DispatchCoalescer, EsRejectedExecutionError, ThreadPool,
    default_coalescer, pool_for_request,
)

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
         "theta", "iota", "kappa", "lam", "mu", "nu", "xi", "omicron", "pi"]

QUERIES = [["alpha"], ["beta", "gamma"], ["delta"], ["pi", "omicron"],
           ["mu", "nu", "xi"], ["kappa"], ["theta", "iota"], ["zeta", "eta"]]


def tiny_pool(**overrides):
    sizes = {"search": 1, "write": 1, "get": 1, "management": 1,
             "snapshot": 1}
    queues = {"search": 1, "write": 1, "get": 1, "management": 1,
              "snapshot": 1}
    sizes.update(overrides.get("sizes", {}))
    queues.update(overrides.get("queues", {}))
    return ThreadPool(sizes=sizes, queue_sizes=queues)


# ---------------------------------------------------------------------------
# named pools: submission, stats, rejection, isolation
# ---------------------------------------------------------------------------


def test_submit_executes_and_counts():
    pool = ThreadPool(sizes={"search": 2})
    try:
        tasks = [pool.submit("search", lambda x: x * 2, i) for i in range(8)]
        assert [t.get(timeout=10) for t in tasks] == [i * 2 for i in range(8)]
        st = pool.stats()["search"]
        assert st["completed"] == 8
        assert st["queue"] == 0 and st["active"] == 0
        assert 1 <= st["largest"] <= 2
        assert st["ewma_ms"] >= 0.0
    finally:
        pool.shutdown()


def test_saturated_pool_rejects_with_429_and_pool_name():
    pool = tiny_pool()
    release = threading.Event()
    try:
        running = pool.submit("search", release.wait, 10)   # occupies the worker
        time.sleep(0.05)
        queued = pool.submit("search", lambda: "queued")    # fills the queue
        with pytest.raises(EsRejectedExecutionError) as ei:
            pool.submit("search", lambda: "rejected")
        assert ei.value.status == 429
        assert ei.value.error_type == "es_rejected_execution_exception"
        assert "search" in str(ei.value)
        assert pool.stats()["search"]["rejected"] == 1
        # the REST error body carries the type the clients retry on
        assert ei.value.to_dict()["type"] == "es_rejected_execution_exception"
        release.set()
        assert queued.get(timeout=10) == "queued"
        assert running.get(timeout=10) is True
    finally:
        release.set()
        pool.shutdown()


def test_write_saturation_does_not_reject_searches():
    pool = tiny_pool()
    release = threading.Event()
    try:
        pool.submit("write", release.wait, 10)
        time.sleep(0.05)
        pool.submit("write", lambda: None)                  # queue full now
        with pytest.raises(EsRejectedExecutionError):
            pool.submit("write", lambda: None)
        # the search stage is a different bounded pool: unaffected
        assert pool.submit("search", lambda: "ok").get(timeout=10) == "ok"
        assert pool.stats()["search"]["rejected"] == 0
        assert pool.stats()["write"]["rejected"] == 1
    finally:
        release.set()
        pool.shutdown()


def test_execute_reenters_inline_from_own_worker():
    """A stage calling itself must run inline, not wait on its own
    single-worker pool (self-deadlock under saturation)."""
    pool = tiny_pool()
    try:
        def nested():
            return pool.execute("search", lambda: "inner")

        assert pool.execute("search", nested) == "inner"
    finally:
        pool.shutdown()


def test_task_errors_propagate_to_waiter():
    pool = ThreadPool(sizes={"management": 1})
    try:
        def boom():
            raise ValueError("broken task")

        with pytest.raises(ValueError, match="broken task"):
            pool.execute("management", boom)
        assert pool.stats()["management"]["completed"] == 1
    finally:
        pool.shutdown()


def test_pool_for_request_classification():
    assert pool_for_request("POST", "/idx/_search") == "search"
    assert pool_for_request("GET", "/_msearch") == "search"
    assert pool_for_request("POST", "/idx/_bulk") == "write"
    assert pool_for_request("POST", "/_reindex") == "write"
    assert pool_for_request("GET", "/idx/_doc/1") == "get"
    assert pool_for_request("PUT", "/idx/_doc/1") == "write"
    assert pool_for_request("GET", "/idx/_source/1") == "get"
    assert pool_for_request("PUT", "/_snapshot/repo/snap") == "snapshot"
    assert pool_for_request("GET", "/_cluster/health") == "management"
    assert pool_for_request("GET", "/") == "management"


def test_http_server_sheds_load_with_429():
    """End to end: a saturated search pool answers 429 with
    es_rejected_execution_exception while management keeps serving."""
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest import (
        HttpServer, RestController, register_handlers,
    )

    node = Node()
    pool = tiny_pool()
    node.thread_pool.shutdown()
    node.thread_pool = pool          # stats routes report the live pool
    rc = RestController()
    register_handlers(node, rc)
    release = threading.Event()
    started = threading.Event()

    def slow_search(req):
        from elasticsearch_tpu.rest.controller import RestResponse

        started.set()
        release.wait(10)
        return RestResponse(body={"slow": True})

    rc.register("GET", "/_slowtest/_search", slow_search)
    server = HttpServer(rc, port=0, thread_pool=pool)
    server.start()
    base = f"http://127.0.0.1:{server.port}"

    def http(path):
        try:
            with urllib.request.urlopen(base + path, timeout=15) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    try:
        t1 = threading.Thread(target=http, args=("/_slowtest/_search",))
        t1.start()
        assert started.wait(10)
        t2 = threading.Thread(target=http, args=("/_slowtest/_search",))
        t2.start()                       # sits in the queue (capacity 1)
        deadline = time.monotonic() + 5
        while pool.stats()["search"]["queue"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        status, body = http("/_slowtest/_search")
        assert status == 429
        assert body["error"]["type"] == "es_rejected_execution_exception"
        assert "search" in body["error"]["reason"]
        # management pool unaffected: the cat route still answers and
        # reports the rejection
        status, _ = http("/_cluster/health")
        assert status == 200
        with urllib.request.urlopen(base + "/_cat/thread_pool/search",
                                    timeout=15) as resp:
            line = resp.read().decode()
        cols = line.split()
        assert cols[:5] == [node.node_name, "search", "1", "1", "1"]
        # PR 9 queue-wait columns: EWMA + histogram p99, both numeric
        assert len(cols) == 7
        float(cols[5])
        float(cols[6])
    finally:
        release.set()
        t1.join(timeout=10)
        t2.join(timeout=10)
        server.stop()
        pool.shutdown()
        node.close()


# ---------------------------------------------------------------------------
# dispatch coalescer: bit-identity with solo execution
# ---------------------------------------------------------------------------


def _build_index(monkeypatch, *, turbo: bool, uuid: str):
    from elasticsearch_tpu.cluster.state import IndexMetadata
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService

    if turbo:
        monkeypatch.setenv("ES_TPU_FORCE_TURBO", "1")
        monkeypatch.setenv("ES_TPU_TURBO_COLD_DF", "8")
    meta = IndexMetadata(
        index="co_" + uuid, uuid=uuid, settings=Settings({}),
        mappings={"properties": {"body": {"type": "text"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(99)
    for i in range(320):
        words = rng.choice(WORDS, size=int(rng.integers(3, 16)))
        svc.index_doc(str(i), {"body": " ".join(words)})
        if i == 140:
            svc.refresh()
    for i in range(0, 50, 9):
        svc.delete_doc(str(i))
    svc.refresh()
    return svc


def _concurrent_dispatch(co, eng, queries, k=10):
    """Each query on its own thread, all released together."""
    results = [None] * len(queries)
    errors = []
    barrier = threading.Barrier(len(queries))

    def worker(i, q):
        try:
            barrier.wait(timeout=10)
            results[i] = co.dispatch(eng, [q], k)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i, q))
               for i, q in enumerate(queries)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    return results


def _assert_rows_equal(got, want, ctx):
    gs, gp, go = got
    ws, wp, wo = want
    assert np.array_equal(gs, ws), ctx
    assert np.array_equal(gp, wp), ctx
    assert np.array_equal(go, wo), ctx


@pytest.mark.parametrize("turbo", [True, False], ids=["turbo", "blockmax"])
def test_coalesced_rows_bit_identical_to_solo(monkeypatch, turbo):
    svc = _build_index(monkeypatch, turbo=turbo, uuid="u_co1" + str(turbo))
    try:
        eng = svc.serving.snapshot().engine("body")
        assert eng.kind == ("turbo" if turbo else "blockmax")
        solo = [eng.search_many([[q]], k=10)[0] for q in QUERIES]
        co = DispatchCoalescer(window_us=500_000, max_batch=len(QUERIES))
        results = _concurrent_dispatch(co, eng, QUERIES)
        for q, got, want in zip(QUERIES, results, solo):
            _assert_rows_equal(
                (got[0][0], got[1][0], got[2][0]),
                (want[0][0], want[1][0], want[2][0]), q)
        st = co.stats()
        assert st["coalesced_queries"] == len(QUERIES)
        # merging actually happened (a full barrier + 500ms window makes
        # fewer dispatches than queries all but certain)
        assert st["coalesced_dispatches"] < len(QUERIES)
        assert st["largest_batch"] > 1
    finally:
        svc.close()


def test_coalescer_keys_by_k_and_window_zero_disables(monkeypatch):
    svc = _build_index(monkeypatch, turbo=False, uuid="u_co2")
    try:
        eng = svc.serving.snapshot().engine("body")
        co = DispatchCoalescer(window_us=0)
        s, p, o = co.dispatch(eng, [["alpha"]], 10)
        want_s, want_p, want_o = eng.search_many([[["alpha"]]], k=10)[0]
        _assert_rows_equal((s[0], p[0], o[0]),
                           (want_s[0], want_p[0], want_o[0]), "win0")
        assert co.stats()["coalesced_dispatches"] == 0
        assert co.stats()["direct_dispatches"] == 1

        # different k values never share a device dispatch
        co2 = DispatchCoalescer(window_us=50_000)
        out = {}

        def run(k):
            out[k] = co2.dispatch(eng, [["beta", "gamma"]], k)

        ts = [threading.Thread(target=run, args=(k,)) for k in (5, 10)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        for k in (5, 10):
            want = eng.search_many([[["beta", "gamma"]]], k=k)[0]
            _assert_rows_equal((out[k][0][0], out[k][1][0], out[k][2][0]),
                               (want[0][0], want[1][0], want[2][0]), k)
            assert out[k][0].shape == (1, k)
    finally:
        svc.close()


def test_mid_window_engine_swap_keeps_batches_separate(monkeypatch):
    """A snapshot refresh mid-window swaps the engine object: waiters on
    the OLD engine finish on the snapshot they captured, new arrivals key
    onto the new engine — both bit-identical to solo execution."""
    svc = _build_index(monkeypatch, turbo=True, uuid="u_co3")
    try:
        snap1 = svc.serving.snapshot()
        eng1 = snap1.engine("body")
        solo1 = eng1.search_many([[["alpha"]]], k=10)[0]

        co = DispatchCoalescer(window_us=400_000)
        got1 = {}

        def old_engine_waiter():
            got1["rows"] = co.dispatch(eng1, [["alpha"]], 10)

        t = threading.Thread(target=old_engine_waiter)
        t.start()
        deadline = time.monotonic() + 5       # old-engine batch is pending
        while co.stats()["coalesced_dispatches"] == 0 \
                and not co._pending and time.monotonic() < deadline:
            time.sleep(0.005)

        # refresh swaps the serving snapshot -> NEW engine object
        svc.index_doc("new", {"body": "alpha alpha alpha fresh"})
        svc.refresh()
        snap2 = svc.serving.snapshot()
        eng2 = snap2.engine("body")
        assert eng2 is not eng1
        rows2 = co.dispatch(eng2, [["alpha"]], 10)
        t.join(timeout=60)

        _assert_rows_equal(
            (got1["rows"][0][0], got1["rows"][1][0], got1["rows"][2][0]),
            (solo1[0][0], solo1[1][0], solo1[2][0]), "old engine")
        solo2 = eng2.search_many([[["alpha"]]], k=10)[0]
        _assert_rows_equal((rows2[0][0], rows2[1][0], rows2[2][0]),
                           (solo2[0][0], solo2[1][0], solo2[2][0]),
                           "new engine")
        assert co.stats()["coalesced_dispatches"] == 2
    finally:
        svc.close()


def test_serving_path_coalesces_concurrent_searches(monkeypatch):
    """End to end through ServingContext.try_search: concurrent REST-level
    singles produce the same responses as sequential solo execution, and
    the process-default coalescer reports merged device dispatches."""
    svc = _build_index(monkeypatch, turbo=True, uuid="u_co4")
    try:
        # pin the legacy fixed-window dispatch path: this test asserts the
        # old coalescer's stats move; the adaptive scheduler has its own
        # suite in test_scheduler.py
        monkeypatch.setenv("ES_TPU_SCHED_MODE", "legacy")
        bodies = [{"query": {"match": {"body": " ".join(q)}}}
                  for q in QUERIES]
        monkeypatch.setenv("ES_TPU_COALESCE_US", "0")
        want = [svc.serving.try_search(b, "query_then_fetch")
                for b in bodies]
        assert all(w is not None for w in want)

        monkeypatch.setenv("ES_TPU_COALESCE_US", "300000")
        before = default_coalescer().stats()["coalesced_dispatches"]
        got = [None] * len(bodies)
        errors = []
        barrier = threading.Barrier(len(bodies))

        def worker(i, b):
            try:
                barrier.wait(timeout=10)
                got[i] = svc.serving.try_search(b, "query_then_fetch")
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i, b))
                   for i, b in enumerate(bodies)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        merged = default_coalescer().stats()["coalesced_dispatches"] - before
        assert 1 <= merged < len(bodies)
        for b, g, w in zip(bodies, got, want):
            assert g is not None, b
            assert [h["_id"] for h in g["hits"]["hits"]] == \
                [h["_id"] for h in w["hits"]["hits"]], b
            assert [h["_score"] for h in g["hits"]["hits"]] == \
                [h["_score"] for h in w["hits"]["hits"]], b
            assert g["hits"]["total"] == w["hits"]["total"], b
    finally:
        svc.close()

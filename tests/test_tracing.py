"""Search flight recorder (PR 9): log-bucketed latency histograms, trace
propagation coordinator -> shard RPC -> back into `profile.tpu`, and the
slowlog ring.

The histogram units pin the mergeability contract (fixed per-kind bucket
boundaries, element-wise sum across nodes); the cluster tests ride the same
in-process harness as test_distributed/test_disruption and assert one trace
id spans the coordinator and every data-node shard context — including
across a PR 6 failover retry, where the failed and the successful rpc_query
attempt land in the SAME trace. The differential test is the acceptance
gate for "zero cost when disabled": sampled vs unsampled responses must be
bit-identical.
"""

import json

import pytest

from elasticsearch_tpu.action.search_action import _COORD_COUNTERS
from elasticsearch_tpu.cluster_node import form_local_cluster
from elasticsearch_tpu.common import faults, metrics, tracing
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest import RestController, register_handlers

MAPPINGS = {"properties": {"n": {"type": "integer"},
                           "body": {"type": "text"}}}

BODY = {"query": {"match": {"body": "common"}}, "size": 10,
        "track_total_hits": True}


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Rings and live histograms are module-global (shared by every node of
    an in-process cluster) — isolate each test from its neighbors."""
    metrics.reset_for_tests()
    tracing.reset_for_tests()
    yield
    metrics.reset_for_tests()
    tracing.reset_for_tests()


def make_cluster(n_data=3):
    names = ["m0"] + [f"d{i}" for i in range(n_data)]
    return form_local_cluster(names, roles={"m0": ("master",)})


def index_body(shards=2, replicas=1):
    return {"settings": {"number_of_shards": shards,
                         "number_of_replicas": replicas},
            "mappings": MAPPINGS}


def bulk_ops(start, count):
    return [{"op": "index", "id": str(i),
             "source": {"n": i, "body": f"word{i % 7} common text"}}
            for i in range(start, start + count)]


def ranked_first(coordinator, store, index="docs", sid=0):
    copies = [r for r in store.current().shard_copies(index, sid)
              if r.state == "STARTED"]
    return coordinator.search_action._rank_copies(copies)[0]


def normalized(resp):
    out = dict(resp)
    out.pop("took", None)
    return out


def has_key(obj, key):
    if isinstance(obj, dict):
        return key in obj or any(has_key(v, key) for v in obj.values())
    if isinstance(obj, list):
        return any(has_key(v, key) for v in obj)
    return False


# --------------------------------------------------------------------------
# histogram units
# --------------------------------------------------------------------------


def test_histogram_bucket_boundaries():
    h = metrics.Histogram("x", "ms")
    # a value exactly on a bound lands in that bound's bucket (bisect_left);
    # just above spills into the next one
    h.record(h.bounds[10])
    h.record(h.bounds[10] * 1.01)
    counts = h.raw()["counts"]
    assert counts[10] == 1 and counts[11] == 1
    # negatives clamp to the first bucket, overflow goes to the final slot
    h.record(-3.0)
    h.record(1e9)
    counts = h.raw()["counts"]
    assert counts[0] == 1 and counts[-1] == 1
    assert h.raw()["max"] == 1e9


def test_histogram_percentiles():
    h = metrics.Histogram("x", "ms")
    for _ in range(90):
        h.record(1.0)
    for _ in range(10):
        h.record(100.0)
    st = h.stats()
    assert st["count"] == 100
    assert st["mean"] == pytest.approx(10.9)
    # bucket upper bound of the quantile observation: p50/p90 in the ~1ms
    # bucket, p99 in the ~100ms bucket (sqrt-2 grid => <=41% quantization)
    assert 1.0 <= st["p50"] <= 1.5
    assert 1.0 <= st["p90"] <= 1.5
    assert 100.0 <= st["p99"] <= 150.0
    assert st["max"] == 100.0
    # overflow observations report the true max, not a bucket bound
    h2 = metrics.Histogram("y", "ms")
    h2.record(5e8)
    assert h2.stats()["p99"] == 5e8


def test_histogram_merge_across_nodes():
    a = metrics.Histogram("a", "ms")
    b = metrics.Histogram("b", "ms")
    for v in range(10):
        a.record(float(v))
    for v in range(100, 110):
        b.record(float(v))
    merged = metrics.merge_summaries([a.raw(), b.raw()])
    assert merged["count"] == 20
    assert merged["max"] == 109.0
    # merged median sits between the two nodes' medians
    assert a.stats()["p50"] <= merged["p50"] <= b.stats()["p50"]
    # merging is exactly element-wise: counts of the merged raw equal sums
    summed = [x + y for x, y in zip(a.raw()["counts"], b.raw()["counts"])]
    assert sum(summed) == 20
    # one-node merge is the identity on the summary
    assert metrics.merge_summaries([a.raw()]) == a.stats()
    # kinds with different boundaries refuse to merge
    c = metrics.Histogram("c", "count")
    with pytest.raises(ValueError):
        metrics.merge_summaries([a.raw(), c.raw()])
    # empty merge yields the zero summary
    assert metrics.merge_summaries([])["count"] == 0


def test_registry_strict_and_lenient():
    with pytest.raises(metrics.UndeclaredHistogramError):
        metrics.observe("not_a_histogram", 1.0)
    # dynamically composed names degrade to a no-op instead of raising
    metrics.observe_if_declared("queue_wait.adhoc_test_pool", 1.0)
    assert metrics.summary("not_a_histogram") is None
    metrics.observe("device", 3.0)
    assert metrics.summary("device")["count"] == 1
    stats = metrics.search_latency_stats()
    for name in ("queue_wait.search", "coalesce_wait", "device", "demux",
                 "fetch", "query", "merge", "rest_total",
                 "coalesce_batch_size", "coalesce_pad_ratio"):
        assert name in stats and "p99" in stats[name]


# --------------------------------------------------------------------------
# trace context units
# --------------------------------------------------------------------------


def test_trace_context_spans_and_totals():
    tc = tracing.TraceContext(node="n1", kind="rest")
    tc.add_span("device", 2.0)
    tc.add_span("device", 3.0, engine="turbo")
    tc.add_span("fetch", 1.5)
    tc.add_span("rest_total", 10.0)
    totals = tc.phase_totals()
    assert totals["device"] == 5.0 and totals["fetch"] == 1.5
    # rest_total envelopes everything else; phase_totals excludes it
    assert "rest_total" not in totals
    with tc.span("merge", shards=2):
        pass
    assert any(s["name"] == "merge" and s["meta"] == {"shards": 2}
               for s in tc.span_dicts())


def test_trace_wire_roundtrip_and_activation():
    tc = tracing.TraceContext(opaque_id="client-7", node="coord")
    child = tracing.child_from_wire(tc.wire(), node="data-1", kind="shard_query")
    assert child.trace_id == tc.trace_id
    assert child.opaque_id == "client-7"
    assert child.node == "data-1" and child.kind == "shard_query"
    assert tracing.child_from_wire(None) is None
    assert tracing.child_from_wire({}) is None
    # activate(None) is a pass-through, real activation nests and restores
    assert tracing.current() is None
    with tracing.activate(None):
        assert tracing.current() is None
    with tracing.activate(tc):
        assert tracing.current() is tc
        with tracing.activate(child):
            assert tracing.current() is child
        assert tracing.current() is tc
    assert tracing.current() is None


def test_slowlog_threshold_parsing():
    class _S:
        def __init__(self, d):
            self._d = d

        def raw(self, key):
            return self._d.get(key)

    key = "index.search.slowlog.threshold.{}.{}"
    th = tracing.slowlog_thresholds(_S({
        key.format("query", "warn"): "500ms",
        key.format("query", "info"): "-1",
        key.format("fetch", "warn"): "1s",
        key.format("fetch", "info"): 250,
    }))
    assert th["query"] == {"warn": 500.0, "info": None}
    assert th["fetch"] == {"warn": 1000.0, "info": 250.0}
    # unparseable values disable rather than blow up the search path
    junk = tracing.slowlog_thresholds(
        _S({key.format("query", "warn"): "soon-ish"}))
    assert junk["query"]["warn"] is None
    assert not tracing.slowlog_configured(_S({}))
    assert tracing.slowlog_configured(
        _S({key.format("query", "warn"): "0ms"}))
    # warn outranks info when both match
    per = {"warn": 100.0, "info": 10.0}
    assert tracing.slowlog_check("query", 150.0, per) == "warn"
    assert tracing.slowlog_check("query", 50.0, per) == "info"
    assert tracing.slowlog_check("query", 5.0, per) is None


# --------------------------------------------------------------------------
# cross-node propagation (the tentpole)
# --------------------------------------------------------------------------


def _seeded_cluster():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")
    return nodes, store, channels


def test_trace_propagates_coordinator_to_shards():
    nodes, store, channels = _seeded_cluster()
    master = nodes[0]
    r = master.search("docs", dict(BODY, profile=True))
    assert r["_shards"]["failed"] == 0

    tpu = r["profile"]["tpu"]
    tid = tpu["trace_id"]
    assert tid and tpu["node"] == "m0"
    assert "rpc_query" in tpu["phases"] and "merge" in tpu["phases"]
    # span sum stays consistent with took: no phase can exceed the request
    assert max(tpu["phases"].values()) <= r["took"] + 250

    same = [t for t in tracing.recent_traces() if t["trace_id"] == tid]
    kinds = {t["kind"] for t in same}
    assert "coordinator" in kinds and "shard_query" in kinds
    # shard contexts ran on data nodes, never on the dedicated master
    shard_nodes = {t["node"] for t in same if t["kind"] == "shard_query"}
    assert shard_nodes and "m0" not in shard_nodes
    # both shards surface a per-shard tpu breakdown in the profile
    assert len(r["profile"]["shards"]) == 2
    for entry in r["profile"]["shards"]:
        assert entry["tpu"]["phases"]["query"] > 0
        assert entry["tpu"]["node"] in shard_nodes
    # internal span transport never leaks into the client response
    assert not has_key(r, "_trace_spans")
    # the shard query phase fed the node-wide histogram too
    assert metrics.summary("query")["count"] >= 2
    assert metrics.summary("merge")["count"] >= 1


def test_failover_retry_shares_one_trace():
    """PR 6 + PR 9: a faulted first attempt and its successful replica
    retry are two rpc_query spans in the SAME trace, the failed one
    carrying the error type and the node it died on."""
    nodes, store, channels = _seeded_cluster()
    master = nodes[0]
    victim = ranked_first(master, store)
    before = dict(_COORD_COUNTERS)
    with faults.inject(f"rpc_query#{victim}:raisexinf"):
        r = master.search("docs", dict(BODY, profile=True))
    assert r["_shards"]["failed"] == 0
    assert _COORD_COUNTERS["shard_retries"] - before["shard_retries"] >= 1

    tid = r["profile"]["tpu"]["trace_id"]
    coord = [t for t in tracing.recent_traces()
             if t["trace_id"] == tid and t["kind"] == "coordinator"]
    assert len(coord) == 1
    rpc = [s for s in coord[0]["spans"] if s["name"] == "rpc_query"]
    failed = [s for s in rpc if "error" in s["meta"]]
    ok = [s for s in rpc if "error" not in s["meta"]]
    assert failed and ok
    assert all(s["meta"]["node"] == victim for s in failed)
    # the shard that failed over still completed — on a different node
    for f in failed:
        retried = [s for s in ok if s["meta"]["shard"] == f["meta"]["shard"]]
        assert retried and all(s["meta"]["node"] != victim for s in retried)
        assert all(s["meta"]["attempt"] > f["meta"]["attempt"]
                   for s in retried)


def test_sampling_differential_bit_identity(monkeypatch):
    """The disabled-by-default acceptance gate: turning the flight recorder
    on (every-request sampling) must not change a single response byte."""
    nodes, store, channels = _seeded_cluster()
    master = nodes[0]
    r_off = master.search("docs", BODY)
    assert tracing.recent_traces() == []      # untraced by default

    monkeypatch.setenv("ES_TPU_TRACE_SAMPLE", "1")
    r_on = master.search("docs", BODY)
    assert normalized(r_on) == normalized(r_off)
    assert not has_key(r_on, "_trace_spans")
    traces = tracing.recent_traces()
    assert any(t["kind"] == "coordinator" for t in traces)
    # shard children joined the sampled trace id
    tid = next(t["trace_id"] for t in traces if t["kind"] == "coordinator")
    assert any(t["kind"] == "shard_query" and t["trace_id"] == tid
               for t in traces)


# --------------------------------------------------------------------------
# slowlog end-to-end through REST
# --------------------------------------------------------------------------


@pytest.fixture()
def env():
    node = Node()
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, params=None, headers=None):
        data = json.dumps(body).encode() if body is not None else None
        resp = rc.dispatch(method, path, params or {}, data, headers=headers)
        return resp.status, json.loads(resp.encode() or b"{}")

    yield node, call
    node.close()


def test_slowlog_end_to_end(env):
    node, call = env
    st, _ = call("PUT", "/s", {"mappings": {"properties": {
        "body": {"type": "text"}}}})
    assert st == 200
    # _disj_servable needs from+size <= the largest partition's doc count,
    # or the fast path declines and no device/demux phases are recorded
    for i in range(32):
        call("PUT", f"/s/_doc/{i}", {"body": f"w{i % 4} common"})
    call("POST", "/s/_refresh")

    # no thresholds configured -> searches never reach the slowlog
    st, r = call("POST", "/s/_search", {"query": {"match": {"body": "common"}}})
    assert st == 200
    st, slow = call("GET", "/_tpu/slowlog")
    assert slow["slowlog"] == [] and slow["query_warn"] == 0

    # thresholds arrive dynamically via _settings (the PR's bugfix: they
    # live on index settings and IndexService parses them effectively)
    st, _ = call("PUT", "/s/_settings", {"index": {"search": {"slowlog": {
        "threshold": {"query": {"warn": "0ms"}}}}}})
    assert st == 200
    svc = node.indices.get("s")
    th = svc.effective_slowlog_thresholds()
    assert th["query"]["warn"] == 0.0 and th["query"]["info"] is None

    st, r = call("POST", "/s/_search",
                 {"query": {"match": {"body": "common"}}},
                 headers={"X-Opaque-Id": "slowlog-e2e"})
    assert st == 200

    st, slow = call("GET", "/_tpu/slowlog")
    assert slow["query_warn"] >= 1
    entry = slow["slowlog"][-1]
    assert entry["phase"] == "query" and entry["level"] == "warn"
    assert entry["index"] == "s" and entry["took_ms"] >= 0
    assert entry["source"] == {"match": {"body": "common"}}
    # slowlog-configured index => the request was traced: the record has a
    # trace id, the client correlation header, and a phase breakdown
    assert entry["trace_id"] and entry["opaque_id"] == "slowlog-e2e"
    assert "device" in entry["phases"] and "fetch" in entry["phases"]
    # the same trace landed in the flight-recorder ring
    st, tr = call("GET", "/_tpu/trace")
    assert any(t["trace_id"] == entry["trace_id"] for t in tr["traces"])

    # and node stats expose both the histograms and the slowlog counters
    st, stats = call("GET", "/_nodes/stats")
    lat = stats["nodes"][node.node_id]["tpu_search_latency"]
    assert lat["rest_total"]["count"] >= 2
    assert lat["device"]["count"] >= 1
    assert lat["fetch"]["count"] >= 1
    assert lat["slowlog"]["query_warn"] >= 1
    assert lat["slowlog"]["ring_entries"] == len(slow["slowlog"])


def test_profile_response_carries_rest_trace(env):
    """Single-node profiled search: the REST layer owns the trace, so
    profile.tpu names the rest context and phases include the fast-path
    device/demux/fetch decomposition."""
    node, call = env
    call("PUT", "/s", {"mappings": {"properties": {
        "body": {"type": "text"}}}})
    for i in range(32):
        call("PUT", f"/s/_doc/{i}", {"body": f"w{i % 4} common"})
    call("POST", "/s/_refresh")

    st, r = call("POST", "/s/_search",
                 {"query": {"match": {"body": "common"}}, "profile": True,
                  "size": 10},
                 headers={"X-Opaque-Id": "prof-1"})
    assert st == 200
    tpu = r["profile"]["tpu"]
    assert tpu["trace_id"] and tpu["opaque_id"] == "prof-1"
    assert {"device", "demux", "fetch"} <= set(tpu["phases"])
    # the profile query tree is still the classic shape next to the
    # tpu section
    assert r["profile"]["shards"][0]["searches"][0]["query"]

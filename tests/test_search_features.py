"""search_after, PIT, scroll, highlight, collapse (VERDICT r2 next #5).

Done-criteria exercised here: stable pagination over many results while
concurrent indexing continues (PIT/scroll pin their snapshot); phrase
match highlighting; collapse dedup with best-hit-per-group semantics.
"""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService, IndicesService

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]


def make_indices(n_docs=500, shards=1):
    ind = IndicesService()
    ind.create_index("t", Settings({"index.number_of_shards": shards}), {
        "properties": {
            "body": {"type": "text"},
            "tag": {"type": "keyword"},
            "rank": {"type": "integer"},
        }}, {})
    svc = ind.get("t")
    rng = np.random.default_rng(11)
    for i in range(n_docs):
        words = rng.choice(WORDS, size=int(rng.integers(3, 12)))
        svc.index_doc(str(i), {"body": " ".join(words),
                               "tag": f"g{i % 7}", "rank": int(i)})
        if i == n_docs // 2:
            svc.refresh()
    svc.refresh()
    return ind, svc


@pytest.fixture(scope="module")
def env():
    ind, svc = make_indices()
    yield ind, svc
    ind.close()


# ---------------- search_after ----------------


def test_search_after_paginates_without_gaps(env):
    _, svc = env
    body = {"query": {"match_all": {}}, "size": 50,
            "sort": [{"rank": "asc"}], "track_total_hits": True}
    seen = []
    after = None
    while True:
        b = dict(body)
        if after is not None:
            b["search_after"] = after
        r = svc.search(b)
        hits = r["hits"]["hits"]
        if not hits:
            break
        seen.extend(h["_id"] for h in hits)
        after = hits[-1]["sort"]
    assert seen == [str(i) for i in range(500)]


def test_search_after_requires_sort(env):
    from elasticsearch_tpu.common.errors import IllegalArgumentError

    _, svc = env
    with pytest.raises(IllegalArgumentError):
        svc._search_dense({"query": {"match_all": {}},
                           "sort": [{"rank": "asc"}],
                           "search_after": [1, 2]})


def test_search_after_score_sort(env):
    _, svc = env
    base = {"query": {"match": {"body": "alpha beta"}}, "size": 20,
            "sort": [{"_score": "desc"}, {"rank": "asc"}]}
    full = svc.search({**base, "size": 60})["hits"]["hits"]
    page1 = svc.search(base)["hits"]["hits"]
    page2 = svc.search({**base, "search_after": page1[-1]["sort"]})["hits"]["hits"]
    got = [h["_id"] for h in page1 + page2]
    assert got == [h["_id"] for h in full[:40]]


# ---------------- scroll ----------------


def test_scroll_stable_under_concurrent_indexing(env):
    ind, svc = env
    r = ind.scroll_start("t", {"query": {"match_all": {}}, "size": 64,
                              "sort": [{"rank": "asc"}]}, 60.0)
    sid = r["_scroll_id"]
    seen = [h["_id"] for h in r["hits"]["hits"]]
    step = 0
    while True:
        # concurrent writes must not affect the pinned snapshot
        svc.index_doc(f"new-{step}", {"body": "alpha", "rank": 10_000 + step})
        if step % 3 == 0:
            svc.refresh()
        step += 1
        r = ind.scroll_continue(sid)
        if not r["hits"]["hits"]:
            break
        seen.extend(h["_id"] for h in r["hits"]["hits"])
    assert seen == [str(i) for i in range(500)]
    assert ind.contexts.release(sid)


def test_scroll_default_score_order(env):
    ind, svc = env
    full = svc.search({"query": {"match": {"body": "gamma"}}, "size": 100,
                       "track_total_hits": True})
    r = ind.scroll_start("t", {"query": {"match": {"body": "gamma"}},
                               "size": 30}, 60.0)
    sid = r["_scroll_id"]
    seen = [(h["_id"], h["_score"]) for h in r["hits"]["hits"]]
    assert all(s is not None for _, s in seen)
    while True:
        r = ind.scroll_continue(sid)
        if not r["hits"]["hits"]:
            break
        seen.extend((h["_id"], h["_score"]) for h in r["hits"]["hits"])
    want = [(h["_id"], h["_score"]) for h in full["hits"]["hits"]]
    assert [i for i, _ in seen][: len(want)] == [i for i, _ in want]
    assert len(seen) == full["hits"]["total"]["value"]
    ind.contexts.release(sid)


# ---------------- PIT ----------------


def test_pit_pins_snapshot(env):
    ind, svc = env
    svc.refresh()   # drain any unrefreshed docs from earlier tests
    pit = ind.open_pit("t", 60.0)
    before = svc.search({"query": {"match_all": {}}, "size": 0,
                         "track_total_hits": True},
                        searchers=ind.contexts.get(pit).extra["searchers"])
    n0 = before["hits"]["total"]["value"]
    for i in range(20):
        svc.index_doc(f"pit-{i}", {"body": "alpha beta", "rank": 0})
    svc.refresh()
    after = svc.search({"query": {"match_all": {}}, "size": 0,
                        "track_total_hits": True},
                       searchers=ind.contexts.get(pit).extra["searchers"])
    assert after["hits"]["total"]["value"] == n0
    live = svc.search({"query": {"match_all": {}}, "size": 0,
                       "track_total_hits": True})
    assert live["hits"]["total"]["value"] == n0 + 20
    assert ind.close_pit(pit)


def test_pit_expiry_reaped(env):
    ind, _ = env
    pit = ind.open_pit("t", 0.01)
    import time

    time.sleep(0.05)
    assert ind.contexts.reap() >= 1
    from elasticsearch_tpu.search.reader_context import SearchContextMissingError

    with pytest.raises(SearchContextMissingError):
        ind.contexts.get(pit)


# ---------------- highlight ----------------


def test_highlight_terms_and_phrase():
    ind = IndicesService()
    ind.create_index("h", Settings({}), {
        "properties": {"body": {"type": "text"}}}, {})
    svc = ind.get("h")
    svc.index_doc("1", {"body": "the quick brown fox jumps over the lazy dog"})
    svc.index_doc("2", {"body": "a quick study of brown bears"})
    svc.refresh()
    r = svc.search({"query": {"match": {"body": "quick brown"}},
                    "highlight": {"fields": {"body": {}}}})
    by_id = {h["_id"]: h for h in r["hits"]["hits"]}
    assert "<em>quick</em>" in by_id["1"]["highlight"]["body"][0]
    assert "<em>brown</em>" in by_id["1"]["highlight"]["body"][0]

    r = svc.search({"query": {"match_phrase": {"body": "quick brown"}},
                    "highlight": {"fields": {"body": {}}}})
    hits = r["hits"]["hits"]
    assert [h["_id"] for h in hits] == ["1"]
    frag = hits[0]["highlight"]["body"][0]
    assert "<em>quick</em> <em>brown</em> fox" in frag
    # doc 2 has both terms but not the phrase: no hit at all
    ind.close()


def test_highlight_fragments_and_tags():
    ind = IndicesService()
    ind.create_index("h2", Settings({}), {
        "properties": {"body": {"type": "text"}}}, {})
    svc = ind.get("h2")
    long_text = ("filler words here. " * 20 + "needle in the haystack. "
                 + "more filler text. " * 20 + "another needle appears. "
                 + "trailing filler. " * 10)
    svc.index_doc("1", {"body": long_text})
    svc.refresh()
    r = svc.search({
        "query": {"term": {"body": "needle"}},
        "highlight": {"fields": {"body": {
            "fragment_size": 60, "number_of_fragments": 2,
            "pre_tags": ["<b>"], "post_tags": ["</b>"]}}}})
    frags = r["hits"]["hits"][0]["highlight"]["body"]
    assert 1 <= len(frags) <= 2
    assert all("<b>needle</b>" in f for f in frags)
    assert all(len(f) < 120 for f in frags)
    ind.close()


# ---------------- collapse ----------------


@pytest.fixture(scope="module")
def collapse_env():
    ind, svc = make_indices(n_docs=300)
    yield ind, svc
    ind.close()


def test_collapse_dedups_by_field(collapse_env):
    _, svc = collapse_env
    r = svc.search({"query": {"match": {"body": "alpha"}},
                    "collapse": {"field": "tag"}, "size": 7})
    hits = r["hits"]["hits"]
    tags = [h["fields"]["tag"][0] for h in hits]
    assert len(tags) == len(set(tags)), "collapse must dedup groups"
    # each returned hit is the BEST of its group: rerun without collapse
    full = svc.search({"query": {"match": {"body": "alpha"}}, "size": 400})
    best_by_tag = {}
    for h in full["hits"]["hits"]:
        t = h["_source"]["tag"]
        best_by_tag.setdefault(t, h["_id"])
    for h in hits:
        assert h["_id"] == best_by_tag[h["fields"]["tag"][0]]


def test_collapse_with_sort(collapse_env):
    _, svc = collapse_env
    r = svc.search({"query": {"match_all": {}},
                    "sort": [{"rank": "desc"}],
                    "collapse": {"field": "tag"}, "size": 7})
    hits = r["hits"]["hits"]
    tags = [h["fields"]["tag"][0] for h in hits]
    assert len(tags) == len(set(tags))
    ranks = [h["sort"][0] for h in hits]
    assert ranks == sorted(ranks, reverse=True)

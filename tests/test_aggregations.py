"""Aggregation framework: collect/reduce/finalize parity with expected values."""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import InternalEngine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import execute_search

MAPPING = {
    "properties": {
        "category": {"type": "keyword"},
        "tags": {"type": "keyword"},
        "price": {"type": "double"},
        "qty": {"type": "integer"},
        "sold_at": {"type": "date"},
        "body": {"type": "text"},
    }
}

DOCS = [
    {"category": "a", "tags": ["x", "y"], "price": 10.0, "qty": 1,
     "sold_at": "2021-01-01T00:00:00Z", "body": "alpha beta"},
    {"category": "a", "tags": ["x"], "price": 20.0, "qty": 2,
     "sold_at": "2021-01-01T06:00:00Z", "body": "alpha"},
    {"category": "b", "tags": ["y"], "price": 30.0, "qty": 3,
     "sold_at": "2021-01-02T00:00:00Z", "body": "beta"},
    {"category": "b", "tags": ["z"], "price": 40.0, "qty": 4,
     "sold_at": "2021-01-02T12:00:00Z", "body": "gamma"},
    {"category": "c", "tags": [], "price": 50.0, "qty": 5,
     "sold_at": "2021-01-03T00:00:00Z", "body": "alpha gamma"},
]


@pytest.fixture(scope="module")
def engine():
    e = InternalEngine(MapperService(dict(MAPPING)))
    for i, d in enumerate(DOCS):
        e.index(str(i), d)
    e.refresh()
    return e


def search(engine, body):
    return execute_search(engine.acquire_searcher(), engine.mapper, body, "idx")


def test_metric_aggs(engine):
    r = search(engine, {"size": 0, "aggs": {
        "mn": {"min": {"field": "price"}},
        "mx": {"max": {"field": "price"}},
        "sm": {"sum": {"field": "price"}},
        "av": {"avg": {"field": "price"}},
        "vc": {"value_count": {"field": "price"}},
        "st": {"stats": {"field": "price"}},
        "es": {"extended_stats": {"field": "price"}},
    }})
    a = r["aggregations"]
    assert a["mn"]["value"] == 10.0
    assert a["mx"]["value"] == 50.0
    assert a["sm"]["value"] == 150.0
    assert a["av"]["value"] == 30.0
    assert a["vc"]["value"] == 5
    assert a["st"] == {"count": 5, "min": 10.0, "max": 50.0, "avg": 30.0, "sum": 150.0}
    assert a["es"]["variance"] == pytest.approx(200.0)
    assert a["es"]["std_deviation"] == pytest.approx(np.sqrt(200.0))


def test_terms_agg_with_sub(engine):
    r = search(engine, {"size": 0, "aggs": {
        "cats": {"terms": {"field": "category"},
                 "aggs": {"avg_price": {"avg": {"field": "price"}}}}}})
    buckets = r["aggregations"]["cats"]["buckets"]
    by_key = {b["key"]: b for b in buckets}
    assert by_key["a"]["doc_count"] == 2
    assert by_key["a"]["avg_price"]["value"] == 15.0
    assert by_key["b"]["doc_count"] == 2
    assert by_key["c"]["avg_price"]["value"] == 50.0
    # default order: count desc
    assert buckets[0]["doc_count"] >= buckets[-1]["doc_count"]


def test_terms_multivalued_and_order_by_subagg(engine):
    r = search(engine, {"size": 0, "aggs": {
        "tags": {"terms": {"field": "tags", "order": {"avg_p": "desc"}},
                 "aggs": {"avg_p": {"avg": {"field": "price"}}}}}})
    buckets = r["aggregations"]["tags"]["buckets"]
    by_key = {b["key"]: b for b in buckets}
    assert by_key["x"]["doc_count"] == 2
    assert by_key["y"]["doc_count"] == 2
    assert by_key["z"]["doc_count"] == 1
    # z avg=40, y avg=20, x avg=15
    assert [b["key"] for b in buckets] == ["z", "y", "x"]


def test_terms_agg_respects_query(engine):
    r = search(engine, {"size": 0, "query": {"range": {"price": {"gte": 25}}},
                        "aggs": {"cats": {"terms": {"field": "category"}}}})
    by_key = {b["key"]: b for b in r["aggregations"]["cats"]["buckets"]}
    assert "a" not in by_key
    assert by_key["b"]["doc_count"] == 2


def test_histogram_and_range(engine):
    r = search(engine, {"size": 0, "aggs": {
        "h": {"histogram": {"field": "price", "interval": 20}},
        "r": {"range": {"field": "price",
                        "ranges": [{"to": 25}, {"from": 25, "to": 45}, {"from": 45}]}},
    }})
    h = r["aggregations"]["h"]["buckets"]
    assert [(b["key"], b["doc_count"]) for b in h] == [(0.0, 1), (20.0, 2), (40.0, 2)]
    rb = r["aggregations"]["r"]["buckets"]
    assert [b["doc_count"] for b in rb] == [2, 2, 1]
    assert rb[0]["to"] == 25.0 and rb[1]["from"] == 25.0


def test_date_histogram(engine):
    r = search(engine, {"size": 0, "aggs": {
        "d": {"date_histogram": {"field": "sold_at", "calendar_interval": "day"}}}})
    buckets = r["aggregations"]["d"]["buckets"]
    assert [b["doc_count"] for b in buckets] == [2, 2, 1]
    assert buckets[0]["key_as_string"].startswith("2021-01-01")


def test_filter_filters_missing_global(engine):
    r = search(engine, {"size": 0, "query": {"term": {"category": "a"}}, "aggs": {
        "expensive": {"filter": {"range": {"price": {"gte": 15}}}},
        "byf": {"filters": {"filters": {"cheap": {"range": {"price": {"lt": 15}}},
                                        "rich": {"range": {"price": {"gte": 15}}}}}},
        "no_tags": {"missing": {"field": "tags"}},
        "all": {"global": {}, "aggs": {"mx": {"max": {"field": "price"}}}},
    }})
    a = r["aggregations"]
    assert a["expensive"]["doc_count"] == 1
    assert a["byf"]["buckets"]["cheap"]["doc_count"] == 1
    assert a["byf"]["buckets"]["rich"]["doc_count"] == 1
    assert a["no_tags"]["doc_count"] == 0   # both 'a' docs have tags
    assert a["all"]["doc_count"] == 5       # global ignores the query
    assert a["all"]["mx"]["value"] == 50.0


def test_cardinality_and_percentiles(engine):
    r = search(engine, {"size": 0, "aggs": {
        "card": {"cardinality": {"field": "category"}},
        "card_n": {"cardinality": {"field": "qty"}},
        "pct": {"percentiles": {"field": "price", "percents": [50.0]}},
        "ranks": {"percentile_ranks": {"field": "price", "values": [30.0]}},
    }})
    a = r["aggregations"]
    assert a["card"]["value"] == 3
    assert a["card_n"]["value"] == 5
    assert a["pct"]["values"]["50.0"] == pytest.approx(30.0, rel=0.2)
    assert a["ranks"]["values"]["30.0"] == pytest.approx(50.0, abs=15)


def test_top_hits_and_weighted_avg(engine):
    r = search(engine, {"size": 0, "aggs": {
        "cats": {"terms": {"field": "category"},
                 "aggs": {"top": {"top_hits": {"size": 1}}}},
        "wavg": {"weighted_avg": {"value": {"field": "price"},
                                  "weight": {"field": "qty"}}},
    }})
    a = r["aggregations"]
    by_key = {b["key"]: b for b in a["cats"]["buckets"]}
    assert by_key["a"]["top"]["hits"]["total"]["value"] == 2
    assert len(by_key["a"]["top"]["hits"]["hits"]) == 1
    # (10*1+20*2+30*3+40*4+50*5)/(1+2+3+4+5) = 550/15
    assert a["wavg"]["value"] == pytest.approx(550 / 15)


def test_pipeline_aggs(engine):
    r = search(engine, {"size": 0, "aggs": {
        "days": {"date_histogram": {"field": "sold_at", "calendar_interval": "day"},
                 "aggs": {"rev": {"sum": {"field": "price"}}}},
        "total_rev": {"sum_bucket": {"buckets_path": "days>rev"}},
        "avg_rev": {"avg_bucket": {"buckets_path": "days>rev"}},
        "max_rev": {"max_bucket": {"buckets_path": "days>rev"}},
        "cum": {"cumulative_sum": {"buckets_path": "days>rev"}},
        "deriv": {"derivative": {"buckets_path": "days>rev"}},
    }})
    a = r["aggregations"]
    # day sums: 30, 70, 50
    assert a["total_rev"]["value"] == 150.0
    assert a["avg_rev"]["value"] == 50.0
    assert a["max_rev"]["value"] == 70.0
    days = a["days"]["buckets"]
    assert [b["cum"]["value"] for b in days] == [30.0, 100.0, 150.0]
    assert days[0]["deriv"]["value"] is None
    assert days[1]["deriv"]["value"] == 40.0


def test_bucket_script_and_selector(engine):
    r = search(engine, {"size": 0, "aggs": {
        "cats": {"terms": {"field": "category"},
                 "aggs": {"rev": {"sum": {"field": "price"}},
                          "n": {"sum": {"field": "qty"}}}},
        "per_unit": {"bucket_script": {
            "buckets_path": {"r": "cats>rev", "n": "cats>n"},
            "script": "r / n"}},
    }})
    # bucket_script applied per bucket of cats
    buckets = r["aggregations"]["cats"]["buckets"]
    by_key = {b["key"]: b for b in buckets}
    assert by_key["a"]["per_unit"]["value"] == pytest.approx(30.0 / 3)


def test_composite_agg(engine):
    r = search(engine, {"size": 0, "aggs": {
        "comp": {"composite": {"size": 2, "sources": [
            {"cat": {"terms": {"field": "category"}}}]}}}})
    comp = r["aggregations"]["comp"]
    assert [b["key"]["cat"] for b in comp["buckets"]] == ["a", "b"]
    assert comp["after_key"] == {"cat": "b"}
    r2 = search(engine, {"size": 0, "aggs": {
        "comp": {"composite": {"size": 2, "after": {"cat": "b"}, "sources": [
            {"cat": {"terms": {"field": "category"}}}]}}}})
    assert [b["key"]["cat"] for b in r2["aggregations"]["comp"]["buckets"]] == ["c"]


def test_multi_segment_reduce(engine):
    # fresh engine, two refreshes -> two segments; reduce must merge
    e = InternalEngine(MapperService(dict(MAPPING)))
    for i, d in enumerate(DOCS[:3]):
        e.index(str(i), d)
    e.refresh()
    for i, d in enumerate(DOCS[3:], start=3):
        e.index(str(i), d)
    e.refresh()
    r = search(e, {"size": 0, "aggs": {
        "cats": {"terms": {"field": "category"}},
        "st": {"stats": {"field": "price"}},
        "card": {"cardinality": {"field": "category"}},
    }})
    a = r["aggregations"]
    by_key = {b["key"]: b for b in a["cats"]["buckets"]}
    assert by_key["b"]["doc_count"] == 2   # b spans both segments
    assert a["st"]["count"] == 5 and a["st"]["sum"] == 150.0
    assert a["card"]["value"] == 3


def test_histogram_empty_bucket_fill(engine):
    e = InternalEngine(MapperService(dict(MAPPING)))
    e.index("1", {"price": 0.0})
    e.index("2", {"price": 60.0})
    e.refresh()
    r = search(e, {"size": 0, "aggs": {
        "h": {"histogram": {"field": "price", "interval": 20}}}})
    buckets = r["aggregations"]["h"]["buckets"]
    assert [(b["key"], b["doc_count"]) for b in buckets] == [
        (0.0, 1), (20.0, 0), (40.0, 0), (60.0, 1)]


def test_parent_pipelines_declared_inside_bucket_agg(engine):
    # the ES-idiomatic placement: derivative/cumsum INSIDE date_histogram
    r = search(engine, {"size": 0, "aggs": {
        "days": {"date_histogram": {"field": "sold_at", "calendar_interval": "day"},
                 "aggs": {"rev": {"sum": {"field": "price"}},
                          "d": {"derivative": {"buckets_path": "rev"}},
                          "c": {"cumulative_sum": {"buckets_path": "rev"}}}}}})
    days = r["aggregations"]["days"]["buckets"]
    assert [b["c"]["value"] for b in days] == [30.0, 100.0, 150.0]
    assert days[1]["d"]["value"] == 40.0


def test_bucket_selector_inside_terms(engine):
    r = search(engine, {"size": 0, "aggs": {
        "cats": {"terms": {"field": "category"},
                 "aggs": {"rev": {"sum": {"field": "price"}},
                          "keep": {"bucket_selector": {
                              "buckets_path": {"r": "rev"},
                              "script": "r > 40"}}}}}})
    keys = [b["key"] for b in r["aggregations"]["cats"]["buckets"]]
    # revenues: a=30, b=70, c=50 -> keep b and c
    assert sorted(keys) == ["b", "c"]


def test_bucket_sort_inside_terms(engine):
    r = search(engine, {"size": 0, "aggs": {
        "cats": {"terms": {"field": "category"},
                 "aggs": {"rev": {"sum": {"field": "price"}},
                          "srt": {"bucket_sort": {
                              "sort": [{"rev": {"order": "desc"}}], "size": 2}}}}}})
    buckets = r["aggregations"]["cats"]["buckets"]
    assert [b["key"] for b in buckets] == ["b", "c"]


def test_median_absolute_deviation(engine):
    r = search(engine, {"size": 0, "aggs": {
        "mad": {"median_absolute_deviation": {"field": "price"}}}})
    # prices 10..50, median 30, deviations [20,10,0,10,20] -> MAD ~10
    assert r["aggregations"]["mad"]["value"] == pytest.approx(10.0, rel=0.5)


def test_fractional_interval_histogram():
    e = InternalEngine(MapperService(dict(MAPPING)))
    e.index("1", {"price": 0.05})
    e.index("2", {"price": 0.35})
    e.refresh()
    r = search(e, {"size": 0, "aggs": {
        "h": {"histogram": {"field": "price", "interval": 0.1}}}})
    buckets = r["aggregations"]["h"]["buckets"]
    assert [(b["key"], b["doc_count"]) for b in buckets] == [
        (0.0, 1), (0.1, 0), (0.2, 0), (0.3, 1)]


def test_bucket_selector_with_params(engine):
    r = search(engine, {"size": 0, "aggs": {
        "cats": {"terms": {"field": "category"},
                 "aggs": {"rev": {"sum": {"field": "price"}},
                          "keep": {"bucket_selector": {
                              "buckets_path": {"r": "rev"},
                              "script": {"source": "r > params['lim']",
                                         "params": {"lim": 40}}}}}}}})
    assert sorted(b["key"] for b in r["aggregations"]["cats"]["buckets"]) == ["b", "c"]


def test_histogram_rejects_bad_interval(engine):
    from elasticsearch_tpu.common.errors import IllegalArgumentError
    for interval in (0, -1):
        with pytest.raises(IllegalArgumentError):
            search(engine, {"size": 0, "aggs": {
                "h": {"histogram": {"field": "price", "interval": interval}}}})


def test_histogram_bucket_explosion_capped():
    from elasticsearch_tpu.common.errors import IllegalArgumentError
    e = InternalEngine(MapperService(dict(MAPPING)))
    e.index("1", {"price": 0.0})
    e.index("2", {"price": 1e9})
    e.refresh()
    with pytest.raises(IllegalArgumentError):
        search(e, {"size": 0, "aggs": {
            "h": {"histogram": {"field": "price", "interval": 0.001}}}})


def test_track_total_hits_clamps(engine):
    r = search(engine, {"size": 0, "track_total_hits": 2,
                        "query": {"range": {"price": {"gte": 0}}}})
    assert r["hits"]["total"] == {"value": 2, "relation": "gte"}


def test_composite_with_sub_aggs(engine):
    r = search(engine, {"size": 0, "aggs": {
        "comp": {"composite": {"sources": [{"cat": {"terms": {"field": "category"}}}]},
                 "aggs": {"rev": {"sum": {"field": "price"}}}}}})
    buckets = r["aggregations"]["comp"]["buckets"]
    by_cat = {b["key"]["cat"]: b for b in buckets}
    assert by_cat["a"]["rev"]["value"] == 30.0
    assert by_cat["b"]["rev"]["value"] == 70.0


def test_top_hits_respects_scores_and_sort(engine):
    # query scores rank 'alpha' docs; top hit must be the best-scoring one
    r = search(engine, {"size": 0, "query": {"match": {"body": "alpha"}}, "aggs": {
        "top": {"top_hits": {"size": 2}},
        "cheapest": {"top_hits": {"size": 1, "sort": [{"price": {"order": "asc"}}]}},
    }})
    top = r["aggregations"]["top"]["hits"]["hits"]
    assert len(top) == 2
    assert top[0]["_score"] >= top[1]["_score"] > 0
    cheapest = r["aggregations"]["cheapest"]["hits"]["hits"]
    assert cheapest[0]["_source"]["price"] == 10.0


def test_terms_device_counts_match_host_path(monkeypatch):
    """SURVEY §7 step 7: the device terms-count kernel must agree with the
    per-term host loop BIT-FOR-BIT (integer doc counts)."""
    import numpy as np

    import elasticsearch_tpu.search.aggregations as agg_mod
    from elasticsearch_tpu.cluster.state import IndexMetadata
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService

    meta = IndexMetadata(index="ta", uuid="u", settings=Settings({}), mappings={
        "properties": {"tag": {"type": "keyword"}, "body": {"type": "text"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(9)
    n = 2000
    for i in range(n):
        tags = [f"t{rng.integers(0, 50)}"]
        if i % 3 == 0:
            tags.append(f"t{rng.integers(0, 50)}")   # multi-valued docs
        svc.index_doc(str(i), {"tag": tags, "body": "w" + str(i % 7)})
    svc.refresh()
    body = {"query": {"match": {"body": "w3"}}, "size": 0,
            "aggs": {"tags": {"terms": {"field": "tag", "size": 60}}}}

    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1)      # force device
    dev = svc._search_dense(body)["aggregations"]["tags"]
    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1 << 60)  # force host
    host = svc._search_dense(body)["aggregations"]["tags"]
    assert dev == host
    assert sum(b["doc_count"] for b in dev["buckets"]) > 0
    svc.close()


def test_histogram_fast_path_matches_subagg_path():
    """The no-subagg vectorized histogram must agree with the per-bucket
    path (forced by adding a trivial sub-agg)."""
    import numpy as np

    from elasticsearch_tpu.cluster.state import IndexMetadata
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService

    meta = IndexMetadata(index="hf", uuid="u", settings=Settings({}), mappings={
        "properties": {"n": {"type": "integer"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(4)
    for i in range(500):
        svc.index_doc(str(i), {"n": int(rng.integers(0, 100))})
    svc.refresh()
    fast = svc._search_dense({"size": 0, "aggs": {
        "h": {"histogram": {"field": "n", "interval": 10}}}})
    slow = svc._search_dense({"size": 0, "aggs": {
        "h": {"histogram": {"field": "n", "interval": 10},
              "aggs": {"c": {"value_count": {"field": "n"}}}}}})
    fast_b = fast["aggregations"]["h"]["buckets"]
    slow_b = [{k: v for k, v in b.items() if k != "c"}
              for b in slow["aggregations"]["h"]["buckets"]]
    assert fast_b == slow_b
    svc.close()

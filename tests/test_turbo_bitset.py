"""Device bitset-intersection differential suite (PR 16).

The bool path's match sets are packed 32-docs-per-lane into uint32
columns next to the int8 impact columns; conjunction masks come from a
blockwise AND / AND-NOT Pallas kernel and the sweep skips chunks whose
intersected mask is all-zero. The contract is unchanged from the dense
coverage-matmul engine it replaces: the device mask is a SUPERSET of
the true match set (clauses beyond the kernel fan-in are dropped from
the mask only) and the exact host rescore re-tests every clause, so
top-k stays BIT-identical to `search_bool_host` on every route — solo,
fused S > 1, split flushes, the dense engine (ES_TPU_BITSET=0), the
galloping host fallback, injected `bitset_intersect` faults, and an
HBM scrub cycle that repairs a corrupted bitset region.

Runs on the host-simulated 8-device CPU mesh from tests/conftest.py
(Pallas kernels interpret on CPU)."""

import numpy as np
import pytest

from elasticsearch_tpu.common import faults, integrity
from elasticsearch_tpu.index.segment import build_field_postings
from elasticsearch_tpu.parallel.spmd import build_stacked_bm25
from elasticsearch_tpu.parallel.turbo import TurboBM25, _intersect_sorted

pytestmark = pytest.mark.multidevice


class _Seg:
    def __init__(self, n_docs, fp):
        self.n_docs = n_docs
        self.postings = {"body": fp}
        self.vectors = {}


def _pcorpus(n_docs, vocab, seed):
    rng = np.random.default_rng(seed)
    probs = 1.0 / np.arange(1, vocab + 1) ** 1.1
    probs /= probs.sum()
    lens = rng.integers(4, 24, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum()), p=probs).astype(np.int64)
    tok_docs = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    bounds = np.concatenate([[0], np.cumsum(lens)])
    tok_pos = (np.arange(len(tokens), dtype=np.int64)
               - np.repeat(bounds[:-1], lens))
    return build_field_postings("body", lens, tok_docs, tokens,
                                [f"t{i}" for i in range(vocab)],
                                token_pos=tok_pos)


def _turbo(fp, n_docs, cold_df=5, hbm=64 << 20, **kw):
    stacked = build_stacked_bm25([_Seg(n_docs, fp)], "body", serve_only=True)
    return TurboBM25(stacked, hbm_budget_bytes=hbm, cold_df=cold_df, **kw)


def _fused(parts, **kw):
    from elasticsearch_tpu.search.serving import TurboEngine, _turbo_mesh

    turbos = [_turbo(fp, n, **kw) for n, fp in parts]
    return TurboEngine(turbos, mesh=_turbo_mesh(len(turbos)))


def _assert_identical(a, b, label):
    (sa, da), (sb, db) = a, b
    assert np.array_equal(np.asarray(da), np.asarray(db)), \
        f"{label}: doc ids differ"
    assert np.array_equal(np.asarray(sa), np.asarray(sb)), \
        f"{label}: scores differ (not bit-identical)"


# every clause kind the intersect kernel has to represent, plus fan-in
# overflow (>8 required, >4 must_not -> subset-AND superset masks)
SPECS = [
    {"must": [("t1", 1.0), ("t3", 1.0)], "should": [("t5", 1.0)]},
    {"must": [("t0", 1.0)], "must_not": ["t2"],
     "should": [("t7", 1.0), ("t9", 0.5)]},
    {"filter": ["t4"], "should": [("t1", 1.0)]},
    {"must": [("t2", 1.0), ("t6", 2.0)], "must_not": ["t1", "t3"],
     "should": [("t0", 1.0)]},
    {"must": [("t5", 1.0)], "should": [("t8", 1.0), ("t10", 1.0)]},
    {"must": [(f"t{i}", 1.0) for i in range(10)]},          # > BITSET_CLAUSES
    {"must": [("t0", 1.0)],
     "must_not": [f"t{i}" for i in range(1, 8)]},           # > BITSET_NEGS
    {"must": [("t1", 1.0)], "filter": ["t0", "t2"], "must_not": ["t30"]},
    {"must": [("absent", 1.0), ("t1", 1.0)]},               # unmatchable
    {"should": [("t3", 1.0), ("t7", 2.0)]},                 # no required
]
K = 10


def test_bitset_solo_bit_identical(monkeypatch):
    monkeypatch.setenv("ES_TPU_BITSET", "1")
    monkeypatch.setenv("ES_TPU_BITSET_HOST_DF", "0")
    t = _turbo(_pcorpus(2500, 40, 7), 2500)
    got = t.search_bool(SPECS, k=K)
    want = t.search_bool_host(SPECS, k=K)
    _assert_identical(got, want, "solo bitset vs host")
    assert t.stats["bool_device"] > 0, "device route never engaged"
    assert t.stats["bitset_packs"] > 0, "bitsets never packed"
    assert t.stats["bitset_blocks_skipped"] > 0, "no chunk ever skipped"
    assert t.stats["bitset_bytes"] == t.bits.nbytes > 0


def test_bitset_dense_ab_identical(monkeypatch):
    """ES_TPU_BITSET=0 keeps the dense coverage-matmul sweep selectable,
    and both engines give the same bits."""
    monkeypatch.setenv("ES_TPU_BITSET_HOST_DF", "0")
    fp = _pcorpus(1800, 36, 8)
    monkeypatch.setenv("ES_TPU_BITSET", "0")
    dense = _turbo(fp, 1800)
    got_dense = dense.search_bool(SPECS, k=K)
    assert dense.bits is None, "dense engine packed bitsets anyway"
    assert dense.stats["bitset_packs"] == 0
    monkeypatch.setenv("ES_TPU_BITSET", "1")
    bits = _turbo(fp, 1800)
    got_bits = bits.search_bool(SPECS, k=K)
    _assert_identical(got_bits, got_dense, "bitset vs dense A/B")
    _assert_identical(got_bits, bits.search_bool_host(SPECS, k=K),
                      "bitset vs host")


def test_bitset_split_flushes(monkeypatch):
    """qc_sizes=(8,) forces one search_bool call through several device
    chunks; every flush runs the intersect + masked sweep and the
    concatenated result stays bit-identical."""
    monkeypatch.setenv("ES_TPU_BITSET", "1")
    monkeypatch.setenv("ES_TPU_BITSET_HOST_DF", "0")
    rng = np.random.default_rng(5)
    specs = list(SPECS)
    for _ in range(20):
        a, b, c = rng.choice(30, size=3, replace=False)
        specs.append({"must": [(f"t{a}", 1.0)], "should": [(f"t{b}", 1.0)],
                      "must_not": [f"t{c}"]})
    t = _turbo(_pcorpus(2200, 40, 9), 2200, qc_sizes=(8,))
    got = t.search_bool(specs, k=K)
    _assert_identical(got, t.search_bool_host(specs, k=K),
                      "split flushes vs host")
    assert t.stats["bool_device"] > 8, "batch did not split across flushes"


def test_bitset_fused_bit_identical(monkeypatch):
    """S=3 fused dispatch (different sizes, vocabularies, and therefore
    per-partition Hp/bitset shapes) against each partition's host
    route."""
    monkeypatch.setenv("ES_TPU_BITSET", "1")
    monkeypatch.setenv("ES_TPU_BITSET_HOST_DF", "0")
    eng = _fused([(1500, _pcorpus(1500, 40, 1)),
                  (900, _pcorpus(900, 56, 2)),
                  (2100, _pcorpus(2100, 32, 3))])
    st = eng._fused()
    per = st.search_bool(SPECS, k=K)
    for si, t in enumerate(st.turbos):
        _assert_identical(per[si], t.search_bool_host(SPECS, k=K),
                          f"fused partition {si} vs host")
    assert st.bits is not None, "fused bitsets never stacked"
    assert sum(t.stats["bitset_blocks_skipped"] for t in st.turbos) > 0
    # ledger cross-check: with the bitset regions packed, each engine's
    # ledgered occupancy stays byte-identical to its hbm_bytes(), and the
    # facade total covers the per-partition and fused caches exactly
    for t in st.turbos:
        assert t._hbm.total_bytes() == t.hbm_bytes()
        assert t.bits.nbytes > 0
    assert st._hbm.total_bytes() == st.hbm_bytes()
    assert eng.hbm_bytes() == (sum(t.hbm_bytes() for t in st.turbos)
                               + st.hbm_bytes())


def test_bitset_gallop_host_fallback(monkeypatch):
    """A threshold above every df routes every bool query to the
    galloping host intersection — same bits, counter moves."""
    monkeypatch.setenv("ES_TPU_BITSET", "1")
    monkeypatch.setenv("ES_TPU_BITSET_HOST_DF", str(1 << 30))
    t = _turbo(_pcorpus(1600, 40, 10), 1600)
    got = t.search_bool(SPECS, k=K)
    _assert_identical(got, t.search_bool_host(SPECS, k=K),
                      "galloped vs host")
    assert t.stats["bitset_gallop"] > 0, "gallop route never engaged"
    assert t.stats["bitset_blocks_skipped"] == 0, \
        "device sweep ran despite gallop threshold"


def test_intersect_sorted_matches_numpy():
    rng = np.random.default_rng(11)
    for na, nb in [(3, 4000), (200, 250), (0, 50), (70, 0), (1, 1)]:
        a = np.unique(rng.integers(0, 10000, size=na).astype(np.int64))
        b = np.unique(rng.integers(0, 10000, size=nb).astype(np.int64))
        got = _intersect_sorted(a, b)
        want = np.intersect1d(a, b)
        assert np.array_equal(np.sort(got), want), (na, nb)


@pytest.mark.faults
def test_bitset_fault_contained_per_partition(monkeypatch):
    """An injected bitset_intersect fault on one partition host-scores
    that partition only — results stay bit-identical and the fault is
    attributed to the faulted partition."""
    monkeypatch.setenv("ES_TPU_BITSET", "1")
    monkeypatch.setenv("ES_TPU_BITSET_HOST_DF", "0")
    eng = _fused([(700, _pcorpus(700, 40, 12)),
                  (900, _pcorpus(900, 32, 13))])
    want = eng._merge3([t.search_bool_host(SPECS, k=K)
                        for t in eng.turbos], len(SPECS), K)
    flog = []
    with faults.inject("bitset_intersect#1:raise@1"):
        got = eng.search_bool(SPECS, k=K, fault_log=flog)
    for g, w, name in zip(got, want, ("scores", "parts", "ords")):
        assert np.array_equal(np.asarray(g), np.asarray(w)), name
    assert any(f.site == "bitset_intersect" and f.partition == 1
               for f in flog)
    # the faulted partition recovers: a clean retry packs and serves the
    # device bitset route again, still bit-identical
    clean = eng.search_bool(SPECS, k=K)
    for g, w, name in zip(clean, want, ("scores", "parts", "ords")):
        assert np.array_equal(np.asarray(g), np.asarray(w)), name


@pytest.mark.faults
def test_bitset_scrub_bitflip_repair(monkeypatch):
    """PR-15 integrity plane over the new region: an injected hbm_region
    flip on cols_bits is detected by the scrubber, repaired by re-packing
    from the (separately scrubbed) column cache, and the repaired engine
    answers bit-identically."""
    monkeypatch.setenv("ES_TPU_BITSET", "1")
    monkeypatch.setenv("ES_TPU_BITSET_HOST_DF", "0")
    fp = _pcorpus(1400, 36, 14)
    control = _turbo(fp, 1400)
    want = control.search_bool(SPECS, k=K)
    _assert_identical(want, control.search_bool_host(SPECS, k=K), "control")

    integrity.reset_scrub_for_tests()      # only the engine below scrubs
    t = _turbo(fp, 1400)
    t.search_bool(SPECS, k=K)              # packs bits, registers region
    assert t.bits is not None

    def cycle():
        return [integrity.scrub_once()
                for _ in range(integrity.scrub_registry_size())]

    cycle()                                # baseline pass: all clean
    m0 = integrity.integrity_stats()["scrub_mismatches"]
    with faults.inject("hbm_region#cols_bits:raise@1x1"):
        results = cycle()
    hit = [r for r in results if r and r["result"] == "mismatch"]
    assert len(hit) == 1 and hit[0]["region"].endswith(".cols_bits")
    st = integrity.integrity_stats()
    assert st["scrub_mismatches"] == m0 + 1
    assert st["scrub_repairs"] >= 1
    _assert_identical(t.search_bool(SPECS, k=K), want,
                      "repaired bitset engine vs control")
    # next cycle is clean again (the repair re-baselined the region)
    cycle()
    assert integrity.integrity_stats()["scrub_mismatches"] == m0 + 1

"""Execute the REFERENCE's YAML REST suites (VERDICT r4 item 5).

The corpus is the reference's declared compatibility contract —
/root/reference/rest-api-spec/src/main/resources/rest-api-spec/test/
(330 files, ~1140 tests; ref: ESClientYamlSuiteTestCase.java). The full
sweep lives in `conf_sweep.py` at the repo root and writes the scorecard
(CONFORMANCE.md + reference_green.json); THIS test replays every test in
the committed green list so a regression in any previously-conformant API
fails CI. Growing the list = rerun the sweep and commit the new file.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tests.conformance.runner import StepFailure, YamlTestRunner

REF = Path("/root/reference/rest-api-spec/src/main/resources/"
           "rest-api-spec/test")
GREEN = json.loads(
    (Path(__file__).parent / "reference_green.json").read_text())


def _load_file(f: Path):
    import yaml

    docs = list(yaml.safe_load_all(f.read_text()))
    setup, tests = None, {}
    for doc in docs:
        if not doc:
            continue
        for name, steps in doc.items():
            if name == "setup":
                setup = steps
            elif name != "teardown":
                tests[name] = steps
    return setup, tests


@pytest.mark.skipif(not REF.exists(), reason="reference corpus unavailable")
def test_reference_green_suites_stay_green():
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest import RestController, register_handlers

    by_file: dict = {}
    for fname, tname in GREEN:
        by_file.setdefault(fname, []).append(tname)

    failures = []
    for fname in sorted(by_file):
        f = REF / fname
        if not f.exists():
            continue
        setup, tests = _load_file(f)
        node = Node()
        rc = RestController()
        register_handlers(node, rc)

        def dispatch(method, path, params, raw):
            r = rc.dispatch(method, path, params, raw)
            return r.status, r.body

        try:
            for tname in by_file[fname]:
                if tname not in tests:
                    continue
                dispatch("DELETE", "/*", {}, None)
                runner = YamlTestRunner(dispatch)
                try:
                    if setup:
                        runner.run_steps(setup)
                    runner.run_steps(tests[tname])
                except (StepFailure, Exception) as e:  # noqa: BLE001
                    failures.append(f"{fname} :: {tname} :: {str(e)[:200]}")
        finally:
            node.close()
    assert not failures, (
        f"{len(failures)} previously-green reference suites regressed:\n"
        + "\n".join(failures[:20]))
    assert len(GREEN) >= 234        # the committed conformance floor

"""YAML REST conformance runner.

Re-designs the reference's compatibility harness (ref:
test/framework/.../rest/yaml/ESClientYamlSuiteTestCase.java executing the
330 suites under rest-api-spec/src/main/resources/rest-api-spec/test/):
suites are YAML documents of `do` steps (an API call) and assertions
(`match`, `length`, `is_true`, `is_false`, `gt`, `lt`, `gte`, `lte`,
`set`). The runner executes them against THIS framework's REST controller
— the same dispatch surface HTTP clients hit — so a green suite is an API
compatibility statement.

Supported skeleton mirrors the reference: each YAML doc section is one
test; a `setup` section runs before each test in the file; `$stashed`
variables from `set` substitute into later steps; `catch` asserts errors.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import yaml

# api name -> (method, path template); path params in {braces} consume from
# the step's params (ref: rest-api-spec/api/*.json definitions)
API_TABLE: Dict[str, Tuple[str, str]] = {
    "indices.create": ("PUT", "/{index}"),
    "indices.delete": ("DELETE", "/{index}"),
    "indices.get": ("GET", "/{index}"),
    "indices.exists": ("HEAD", "/{index}"),
    "indices.get_mapping": ("GET", "/{index}/_mapping"),
    "indices.put_mapping": ("PUT", "/{index}/_mapping"),
    "indices.refresh": ("POST", "/{index}/_refresh"),
    "indices.flush": ("POST", "/{index}/_flush"),
    "indices.forcemerge": ("POST", "/{index}/_forcemerge"),
    "indices.stats": ("GET", "/{index}/_stats"),
    "indices.get_alias": ("GET", "/{index}/_alias"),
    "indices.update_aliases": ("POST", "/_aliases"),
    "indices.analyze": ("POST", "/{index}/_analyze"),
    "index": ("PUT", "/{index}/_doc/{id}"),
    "create": ("PUT", "/{index}/_create/{id}"),
    "get": ("GET", "/{index}/_doc/{id}"),
    "exists": ("HEAD", "/{index}/_doc/{id}"),
    "get_source": ("GET", "/{index}/_source/{id}"),
    "delete": ("DELETE", "/{index}/_doc/{id}"),
    "update": ("POST", "/{index}/_update/{id}"),
    "mget": ("POST", "/_mget"),
    "bulk": ("POST", "/_bulk"),
    "search": ("POST", "/{index}/_search"),
    "msearch": ("POST", "/_msearch"),
    "count": ("POST", "/{index}/_count"),
    "scroll": ("POST", "/_search/scroll"),
    "clear_scroll": ("DELETE", "/_search/scroll"),
    "open_point_in_time": ("POST", "/{index}/_pit"),
    "close_point_in_time": ("DELETE", "/_pit"),
    "delete_by_query": ("POST", "/{index}/_delete_by_query"),
    "update_by_query": ("POST", "/{index}/_update_by_query"),
    "cluster.health": ("GET", "/_cluster/health"),
    "cluster.state": ("GET", "/_cluster/state"),
    "cluster.stats": ("GET", "/_cluster/stats"),
    "nodes.info": ("GET", "/_nodes"),
    "nodes.stats": ("GET", "/_nodes/stats"),
    "cat.indices": ("GET", "/_cat/indices"),
    "cat.count": ("GET", "/_cat/count"),
    "cat.health": ("GET", "/_cat/health"),
    "cat.thread_pool": ("GET", "/_cat/thread_pool"),
    "cat.shards": ("GET", "/_cat/shards"),
    "tasks.list": ("GET", "/_tasks"),
    "ingest.put_pipeline": ("PUT", "/_ingest/pipeline/{id}"),
    "ingest.get_pipeline": ("GET", "/_ingest/pipeline/{id}"),
    "ingest.delete_pipeline": ("DELETE", "/_ingest/pipeline/{id}"),
    "ingest.simulate": ("POST", "/_ingest/pipeline/_simulate"),
    "snapshot.create_repository": ("PUT", "/_snapshot/{repository}"),
    "snapshot.create": ("PUT", "/_snapshot/{repository}/{snapshot}"),
    "snapshot.get": ("GET", "/_snapshot/{repository}/{snapshot}"),
    "snapshot.delete": ("DELETE", "/_snapshot/{repository}/{snapshot}"),
    "snapshot.restore": ("POST", "/_snapshot/{repository}/{snapshot}/_restore"),
    "info": ("GET", "/"),
    "reindex": ("POST", "/_reindex"),
    "field_caps": ("POST", "/{index}/_field_caps"),
    "explain": ("POST", "/{index}/_explain/{id}"),
    "indices.put_index_template": ("PUT", "/_index_template/{name}"),
    "indices.get_index_template": ("GET", "/_index_template/{name}"),
    "indices.delete_index_template": ("DELETE", "/_index_template/{name}"),
    "cluster.get_settings": ("GET", "/_cluster/settings"),
    "cluster.put_settings": ("PUT", "/_cluster/settings"),
    "indices.close": ("POST", "/{index}/_close"),
    "indices.open": ("POST", "/{index}/_open"),
    "indices.rollover": ("POST", "/{alias}/_rollover/{new_index}"),
    "indices.shrink": ("PUT", "/{index}/_shrink/{target}"),
    "indices.split": ("PUT", "/{index}/_split/{target}"),
    "indices.clone": ("PUT", "/{index}/_clone/{target}"),
    "indices.put_alias": ("PUT", "/{index}/_alias/{name}"),
    "indices.delete_alias": ("DELETE", "/{index}/_alias/{name}"),
    "indices.exists_alias": ("HEAD", "/{index}/_alias/{name}"),
    "indices.get_settings": ("GET", "/{index}/_settings"),
    "indices.put_settings": ("PUT", "/{index}/_settings"),
    "indices.get_field_mapping": ("GET", "/{index}/_mapping/field/{fields}"),
    "indices.put_template": ("PUT", "/_template/{name}"),
    "indices.get_template": ("GET", "/_template/{name}"),
    "indices.delete_template": ("DELETE", "/_template/{name}"),
    "indices.exists_template": ("HEAD", "/_template/{name}"),
    "indices.exists_index_template": ("HEAD", "/_index_template/{name}"),
    "cat.aliases": ("GET", "/_cat/aliases"),
    "cat.templates": ("GET", "/_cat/templates"),
    "cat.allocation": ("GET", "/_cat/allocation"),
    "cat.segments": ("GET", "/_cat/segments"),
    "termvectors": ("POST", "/{index}/_termvectors/{id}"),
    "rank_eval": ("POST", "/{index}/_rank_eval"),
}

_NDJSON_APIS = {"bulk", "msearch"}
# bulk/msearch accept a default index in the path
API_TABLE["bulk"] = ("POST", "/{index}/_bulk")
API_TABLE["msearch"] = ("POST", "/{index}/_msearch")


class StepFailure(AssertionError):
    pass


class YamlTestRunner:
    """Executes one suite file against a fresh node's RestController."""

    def __init__(self, dispatch):
        """dispatch(method, path, params, raw_body) -> (status, body_dict)"""
        self.dispatch = dispatch
        self.stash: Dict[str, Any] = {}
        self.last_response: Any = None
        self.last_status: int = 0

    # ---- value plumbing ----

    def _sub(self, value):
        """$var substitution into strings/structures."""
        if isinstance(value, str):
            if value.startswith("$"):
                return self.stash.get(value[1:], value)
            return re.sub(r"\$\{(\w+)\}",
                          lambda m: str(self.stash.get(m.group(1), "")), value)
        if isinstance(value, dict):
            return {k: self._sub(v) for k, v in value.items()}
        if isinstance(value, list):
            return [self._sub(v) for v in value]
        return value

    def lookup(self, path: str):
        """Dotted/escaped path into the last response ('' = whole body).
        `\\.` escapes literal dots in keys (field names)."""
        if path in ("", "$body"):
            return self.last_response
        node = self.last_response
        parts = re.split(r"(?<!\\)\.", path)
        for pi, raw in enumerate(parts):
            p = raw.replace("\\.", ".")
            p = self._sub(p)
            if p == "_arbitrary_key_":
                # 'arbitrary_key' feature: as the LAST component it yields
                # any KEY (suites stash node ids); mid-path it descends
                # into that key's value
                if not isinstance(node, dict) or not node:
                    raise StepFailure(f"path [{path}]: no keys for "
                                      "_arbitrary_key_")
                key = sorted(node)[0]
                node = key if pi == len(parts) - 1 else node[key]
                continue
            if isinstance(node, list):
                node = node[int(p)]
            elif isinstance(node, dict):
                if p not in node:
                    raise StepFailure(f"path [{path}]: key [{p}] missing "
                                      f"in {json.dumps(node)[:300]}")
                node = node[p]
            else:
                raise StepFailure(f"path [{path}]: cannot descend into "
                                  f"{type(node).__name__}")
        return node

    # ---- steps ----

    def run_do(self, spec: dict) -> None:
        spec = dict(spec)
        catch = spec.pop("catch", None)
        headers = spec.pop("headers", None)  # accepted, unused
        spec.pop("warnings", None)           # deprecation warnings: not
        spec.pop("allowed_warnings", None)   # emitted by this framework
        spec.pop("allowed_warnings_regex", None)
        spec.pop("warnings_regex", None)
        spec.pop("node_selector", None)
        if len(spec) != 1:
            raise StepFailure(f"do step must name one api: {list(spec)}")
        api, params = next(iter(spec.items()))
        params = self._sub(params or {})
        if api not in API_TABLE:
            raise StepFailure(f"unsupported api [{api}]")
        method, template = API_TABLE[api]
        body = params.pop("body", None)
        # optional path params collapse (e.g. /{index}/_refresh -> /_refresh,
        # /{index}/_doc/{id} without id -> auto-id POST), multi-valued
        # params join with commas — mirroring the rest-api-spec url variants
        segs = []
        for seg in template.split("/"):
            names = re.findall(r"\{(\w+)\}", seg)
            if not names:
                segs.append(seg)
                continue
            val = params.pop(names[0], None)
            if val is None:
                segs.append(None)
            elif isinstance(val, list):
                segs.append(",".join(str(v) for v in val))
            else:
                segs.append(str(val))
        path = "/".join(s for s in segs if s is not None)
        if not path.startswith("/"):
            path = "/" + path
        if api in ("index", "create") and path.endswith("/_doc"):
            method = "POST"              # auto-generated id variant
        if api in _NDJSON_APIS:
            if isinstance(body, (str, bytes)):
                raw = body.encode() if isinstance(body, str) else body
            else:
                lines = body if isinstance(body, list) else [body]
                raw = ("\n".join(
                    ln if isinstance(ln, str) else json.dumps(ln)
                    for ln in lines) + "\n").encode()
        elif body is not None:
            raw = body.encode() if isinstance(body, str) else \
                json.dumps(body).encode()
        else:
            raw = None
        qparams = {k: ("true" if v is True else
                       "false" if v is False else str(v))
                   for k, v in params.items()}
        status, resp = self.dispatch(method, path, qparams, raw)
        self.last_status = status
        self.last_response = resp
        if method == "HEAD" and catch is None:
            # exists-style APIs are boolean: 404 is `false`, not an error
            # (ref: ClientYamlTestResponse for HEAD)
            self.last_response = status < 400
            return
        if catch is not None:
            if status < 400:
                raise StepFailure(
                    f"[{api}] expected error [{catch}], got {status}")
            self._check_catch(catch, status, resp)
        elif status >= 400:
            raise StepFailure(f"[{api}] failed [{status}]: "
                              f"{json.dumps(resp)[:400]}")

    def _check_catch(self, catch: str, status: int, resp) -> None:
        table = {"missing": 404, "conflict": 409, "bad_request": 400,
                 "request": None, "param": 400, "unavailable": 503,
                 "forbidden": 403}
        if catch.startswith("/") and catch.endswith("/"):
            blob = json.dumps(resp)
            if re.search(catch[1:-1], blob) is None:
                raise StepFailure(f"error body does not match {catch}: "
                                  f"{blob[:300]}")
            return
        want = table.get(catch)
        if want is not None and status != want:
            raise StepFailure(f"expected [{catch}]={want}, got {status}")

    def run_assert(self, kind: str, spec) -> None:
        if kind == "match":
            for path, want in spec.items():
                got = self.lookup(path)
                want = self._sub(want)
                if isinstance(want, str) and want.startswith("/") \
                        and want.endswith("/") and len(want) > 1:
                    if re.search(want[1:-1].strip(), str(got), re.X) is None:
                        raise StepFailure(
                            f"match {path}: [{got}] !~ {want}")
                elif got != want:
                    raise StepFailure(f"match {path}: got "
                                      f"{json.dumps(got)[:200]} want "
                                      f"{json.dumps(want)[:200]}")
        elif kind == "length":
            for path, want in spec.items():
                got = self.lookup(path)
                if len(got) != int(self._sub(want)):
                    raise StepFailure(
                        f"length {path}: {len(got)} != {want}")
        elif kind in ("is_true", "is_false"):
            got = self.lookup(spec if isinstance(spec, str) else "")
            truthy = got not in (None, False, "", 0, [], {})
            if truthy != (kind == "is_true"):
                raise StepFailure(f"{kind} {spec}: value was {got!r}")
        elif kind in ("gt", "lt", "gte", "lte"):
            import operator

            ops = {"gt": operator.gt, "lt": operator.lt,
                   "gte": operator.ge, "lte": operator.le}
            for path, want in spec.items():
                got = self.lookup(path)
                if not ops[kind](float(got), float(self._sub(want))):
                    raise StepFailure(f"{kind} {path}: {got} vs {want}")
        elif kind == "set":
            for path, var in spec.items():
                self.stash[var] = self.lookup(path)
        else:
            raise StepFailure(f"unsupported assertion [{kind}]")

    def run_steps(self, steps: List[dict]) -> None:
        for step in steps:
            if not isinstance(step, dict) or len(step) != 1:
                raise StepFailure(f"malformed step {step}")
            kind, spec = next(iter(step.items()))
            if kind == "do":
                self.run_do(spec)
            elif kind == "skip":
                continue
            else:
                self.run_assert(kind, spec)


def load_suites(directory: Path) -> List[Tuple[str, str, Optional[list], list]]:
    """[(file, test name, setup steps, test steps)] over every suite file."""
    out = []
    for f in sorted(directory.glob("*.yml")) + sorted(directory.glob("*.yaml")):
        docs = list(yaml.safe_load_all(f.read_text()))
        setup = None
        tests = []
        for doc in docs:
            if not doc:
                continue
            for name, steps in doc.items():
                if name == "setup":
                    setup = steps
                elif name == "teardown":
                    continue
                else:
                    tests.append((name, steps))
        for name, steps in tests:
            out.append((f.name, name, setup, steps))
    return out

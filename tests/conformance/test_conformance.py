"""Execute every YAML conformance suite against a fresh node.

`pytest tests/conformance` reports N/M suites green — the measurable API
compatibility contract (SURVEY §4 / VERDICT r2 next #10). Each test runs
against its own Node through the same RestController dispatch HTTP hits.
"""

import json
import shutil
from pathlib import Path

import pytest

from tests.conformance.runner import StepFailure, YamlTestRunner, load_suites

SUITES = load_suites(Path(__file__).parent / "suites")


@pytest.fixture()
def dispatch():
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest import RestController, register_handlers

    shutil.rmtree("/tmp/es_tpu_conformance_repo", ignore_errors=True)
    node = Node()
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, params, raw):
        resp = rc.dispatch(method, path, params, raw)
        data = resp.encode()
        try:
            body = json.loads(data) if data else {}
        except json.JSONDecodeError:
            body = {"_raw": data.decode(errors="replace")}
        return resp.status, body

    yield call
    node.close()


@pytest.mark.parametrize(
    "fname,name,setup,steps", SUITES,
    ids=[f"{f}::{n}" for f, n, _, _ in SUITES])
def test_suite(dispatch, fname, name, setup, steps):
    runner = YamlTestRunner(dispatch)
    if setup:
        runner.run_steps(setup)
    runner.run_steps(steps)

"""Bounded coordinator reduce + indexing backpressure (VERDICT r4 item 8;
ref: action/search/QueryPhaseResultConsumer.java:52,
index/IndexingPressure.java:1) and the data-only agg wire codec
(ADVICE r4)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.indexing_pressure import (
    EsRejectedExecutionError, IndexingPressure,
)
from elasticsearch_tpu.common.wire import decode_value, encode_value


# ------------------------------------------------------------- wire ----


def test_wire_roundtrip_nested():
    val = {
        "sum": np.float64(3.5),
        "arr": np.arange(12, dtype=np.int32).reshape(3, 4),
        "buckets": [{"key": ("a", 1), "docs": 5}, {"key": ("b", 2),
                    "docs": 7}],
        "keys": {("composite", 3): [1.0, float("inf"), float("nan")]},
        "flags": {True, 1, "x"} and {"x", "y"},
        "none": None,
        "raw": b"\x00\x01",
    }
    out = decode_value(encode_value(val))
    assert out["sum"] == 3.5 and isinstance(out["sum"], np.float64)
    assert np.array_equal(out["arr"], val["arr"])
    assert out["buckets"][0]["key"] == ("a", 1)
    k = ("composite", 3)
    assert out["keys"][k][1] == float("inf")
    assert out["keys"][k][2] != out["keys"][k][2]      # nan
    assert out["raw"] == b"\x00\x01"


def test_wire_rejects_code_bearing_types():
    import pytest as _pytest

    from elasticsearch_tpu.common.wire import WireError

    with _pytest.raises(WireError):
        encode_value(lambda: 1)
    with _pytest.raises(WireError):
        encode_value(object())


def test_wire_is_json_safe():
    import json

    enc = encode_value({"a": np.ones(3), "b": [(1, 2)]})
    json.loads(json.dumps(enc))     # must survive a JSON transport hop


# -------------------------------------------------- indexing pressure ----


def test_indexing_pressure_rejects_over_limit():
    ip = IndexingPressure(limit_bytes=1000)
    with ip.coordinating(800):
        with pytest.raises(EsRejectedExecutionError):
            with ip.coordinating(300):
                pass
        # released reservations recover capacity
    with ip.coordinating(900):
        pass
    st = ip.stats()["memory"]
    assert st["total"]["coordinating_rejections"] == 1
    assert st["current"]["all_in_bytes"] == 0


def test_indexing_pressure_replica_headroom():
    ip = IndexingPressure(limit_bytes=1000)
    with ip.coordinating(900):
        # replica ops ride the 1.5x limit so replication can't deadlock
        with ip.replica(400):
            pass
        with pytest.raises(EsRejectedExecutionError):
            with ip.replica(700):
                pass


def test_rest_bulk_flood_gets_429():
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest import RestController, register_handlers

    node = Node(settings=Settings(
        {"indexing_pressure.memory.limit": 2048}))
    rc = RestController()
    register_handlers(node, rc)
    try:
        node.create_index("bp", {})
        small = '{"index":{"_index":"bp","_id":"1"}}\n{"f":"v"}\n'
        r = rc.dispatch("POST", "/_bulk", {}, small)
        assert r.status == 200, r.body
        big = ('{"index":{"_index":"bp","_id":"2"}}\n{"f":"'
               + "x" * 4096 + '"}\n')
        r = rc.dispatch("POST", "/_bulk", {}, big)
        assert r.status == 429, r.body
        assert "es_rejected_execution_exception" in str(r.body)
        # capacity recovers once the rejected request unwinds
        r = rc.dispatch("POST", "/_bulk", {}, small)
        assert r.status == 200
        # and the rejection is visible in node stats
        st = rc.dispatch("GET", "/_nodes/stats", {}, None)
        ip = st.body["nodes"][node.node_id]["indexing_pressure"]
        assert ip["memory"]["total"]["coordinating_rejections"] == 1
    finally:
        node.close()


# ------------------------------------------- bounded coordinator reduce ----


def test_incremental_reduce_bounds_window_and_matches_full():
    from elasticsearch_tpu.action.search_action import (
        _QueryPhaseResultConsumer,
    )
    from elasticsearch_tpu.common.breaker import CircuitBreaker

    rng = np.random.default_rng(5)
    body = {"size": 10, "batched_reduce_size": 4,
            "aggs": {"m": {"max": {"field": "n"}}}}
    breaker = CircuitBreaker("request", 64 << 20)
    c = _QueryPhaseResultConsumer(body, sort=None, k=10, breaker=breaker)
    all_hits = []
    for si in range(20):                       # 20 shards, 10 hits each
        hits = []
        for j in range(10):
            score = float(rng.random())
            h = {"leaf_idx": 0, "ord": j, "score": score,
                 "global_ord": j, "sort_values": None}
            hits.append(h)
            all_hits.append((score, si, j))
        c.consume(si, {"total": 10, "relation": "eq", "hits": hits,
                       "aggs": encode_value({"m": {"max": np.float64(si)}})})
        # bounded: never more than batch x per-shard hits + window pending
        assert len(c.window) <= 10
    window, agg_state = c.finish()
    assert c.n_reduce_steps >= 5               # reduced incrementally
    assert breaker.used_bytes == 0             # everything released
    assert c.total == 200
    # identical to a full sort of every hit
    all_hits.sort(key=lambda t: (-t[0], t[1], t[2]))
    expect = [(si, j) for _, si, j in all_hits[:10]]
    assert [(si, h["ord"]) for si, h in window] == expect


def test_incremental_reduce_breaker_trips_on_huge_partials():
    from elasticsearch_tpu.action.search_action import (
        _QueryPhaseResultConsumer,
    )
    from elasticsearch_tpu.common.breaker import CircuitBreaker
    from elasticsearch_tpu.common.errors import CircuitBreakingError

    body = {"size": 1, "batched_reduce_size": 512}   # no fold before trip
    breaker = CircuitBreaker("request", 1024)
    c = _QueryPhaseResultConsumer(body, sort=None, k=1, breaker=breaker)
    part = encode_value({"big": np.zeros(4096, np.float64)})
    with pytest.raises(CircuitBreakingError):
        for si in range(10):
            c.consume(si, {"total": 0, "relation": "eq", "hits": [],
                           "aggs": part})




def test_consumer_release_frees_reserved_bytes_after_trip():
    """The coordinator's error path must release pending-partial breaker
    bytes (consumer.release in SearchActionService's except) — a tripped
    search used to leave _reserved accounted forever."""
    from elasticsearch_tpu.action.search_action import (
        _QueryPhaseResultConsumer,
    )
    from elasticsearch_tpu.common.breaker import CircuitBreaker
    from elasticsearch_tpu.common.errors import CircuitBreakingError

    body = {"size": 1, "batched_reduce_size": 512}
    # limit fits a few partials: the trip's own bytes roll back, but the
    # EARLIER consumes' reservations stay accounted in _reserved
    part = encode_value({"big": np.zeros(512, np.float64)})
    breaker = CircuitBreaker("request", 3 * 8 * 512)
    c = _QueryPhaseResultConsumer(body, sort=None, k=1, breaker=breaker)
    with pytest.raises(CircuitBreakingError):
        for si in range(10):
            c.consume(si, {"total": 0, "relation": "eq", "hits": [],
                           "aggs": part})
    assert breaker.used_bytes > 0              # the leak being tested
    c.release()
    assert breaker.used_bytes == 0
    c.release()                                # idempotent
    assert breaker.used_bytes == 0


def test_cluster_node_shares_one_indexing_pressure():
    """Every write stage on a node accounts against ONE IndexingPressure
    (ref: IndexingPressure.java is a node-level singleton) — the shard
    service must reuse the node's instance, not grow its own budget."""
    from elasticsearch_tpu.cluster_node import form_local_cluster

    nodes, store, channels = form_local_cluster(["a", "b"])
    try:
        for node in nodes:
            assert node.shard_service.indexing_pressure \
                is node.indexing_pressure
    finally:
        for node in nodes:
            node.close()

import pytest

from elasticsearch_tpu.common import (
    CircuitBreaker,
    CircuitBreakingError,
    ClusterSettings,
    HierarchyCircuitBreakerService,
    IllegalArgumentError,
    Setting,
    Settings,
)
from elasticsearch_tpu.common.settings import parse_bytes_value, parse_time_value


def test_settings_flatten_and_nested_roundtrip():
    s = Settings({"index": {"number_of_shards": 4, "refresh_interval": "1s"}, "cluster.name": "x"})
    assert s.raw("index.number_of_shards") == 4
    assert s.raw("cluster.name") == "x"
    nested = s.as_nested_dict()
    assert nested["index"]["number_of_shards"] == 4


def test_settings_updates_and_null_reset():
    s = Settings({"a.b": 1, "a.c": 2})
    s2 = s.with_updates({"a.b": 5, "a.c": None})
    assert s2.raw("a.b") == 5
    assert s2.raw("a.c") is None
    assert s.raw("a.b") == 1  # immutable


def test_typed_settings():
    num_shards = Setting.int_setting("index.number_of_shards", 1, min_value=1, scope="index")
    refresh = Setting.time_setting("index.refresh_interval", "1s", dynamic=True)
    s = Settings({"index.number_of_shards": "4"})
    assert num_shards.get(s) == 4
    assert refresh.get(s) == 1.0
    assert refresh.get(Settings({"index.refresh_interval": "500ms"})) == 0.5


def test_time_and_bytes_parsing():
    assert parse_time_value("30s") == 30.0
    assert parse_time_value("2m") == 120.0
    assert parse_time_value("100ms") == 0.1
    assert parse_bytes_value("1kb") == 1024
    assert parse_bytes_value("2gb") == 2 << 30
    with pytest.raises(IllegalArgumentError):
        parse_time_value("abc")


def test_cluster_settings_dynamic_update_and_consumer():
    refresh = Setting.time_setting("index.refresh_interval", "1s", dynamic=True)
    static = Setting.int_setting("node.processors", 4)
    cs = ClusterSettings(Settings(), [refresh, static])
    seen = []
    cs.add_settings_update_consumer(refresh, seen.append)
    cs.apply({"index.refresh_interval": "5s"})
    assert seen == [5.0]
    with pytest.raises(IllegalArgumentError):
        cs.apply({"node.processors": 8})  # not dynamic
    with pytest.raises(IllegalArgumentError):
        cs.apply({"nope.unknown": 1})  # unregistered


def test_circuit_breaker_trips_and_releases():
    b = CircuitBreaker("request", limit_bytes=1000)
    b.add_estimate_bytes_and_maybe_break(800, "agg")
    with pytest.raises(CircuitBreakingError):
        b.add_estimate_bytes_and_maybe_break(300, "agg2")
    assert b.used_bytes == 800
    assert b.trip_count == 1
    b.release(800)
    assert b.used_bytes == 0
    b.add_estimate_bytes_and_maybe_break(900, "ok")


def test_hierarchy_breaker_parent_enforced():
    svc = HierarchyCircuitBreakerService(total_limit_bytes=1000)
    req = svc.get_breaker("request")
    fd = svc.get_breaker("fielddata")
    req.add_estimate_bytes_and_maybe_break(500, "r")
    with pytest.raises(CircuitBreakingError):
        fd.add_estimate_bytes_and_maybe_break(390, "f")  # fielddata limit 400, overhead 1.03
    # parent trips even when the child alone would allow it
    with pytest.raises(CircuitBreakingError):
        req.add_estimate_bytes_and_maybe_break(501, "r2")
    assert svc.get_breaker("request").used_bytes == 500


def test_parent_trip_rolls_back_child_accounting():
    """A parent-level trip must leave the CHILD's accounting untouched:
    the child tentatively adds, the parent refuses, the child rolls
    back — repeated refusals never leak reserved bytes."""
    parent = CircuitBreaker("parent", limit_bytes=1000)
    child = CircuitBreaker("request", limit_bytes=10_000, parent=parent)
    child.add_estimate_bytes_and_maybe_break(900, "warm")
    for _ in range(5):
        with pytest.raises(CircuitBreakingError):
            child.add_estimate_bytes_and_maybe_break(200, "over")
    assert child.used_bytes == 900
    assert parent.used_bytes == 900
    assert parent.trip_count == 5
    assert child.trip_count == 0          # the PARENT tripped, not it
    child.release(900)
    assert child.used_bytes == 0 and parent.used_bytes == 0


def test_breaker_concurrent_adds_consistent_accounting():
    """Threads racing add_estimate_bytes_and_maybe_break against child +
    parent limits: every ACCEPTED reservation is fully accounted on both
    levels, every REFUSED one fully rolled back — no partial states,
    and trip counts equal the number of refusals."""
    import threading

    parent = CircuitBreaker("parent", limit_bytes=50_000)
    children = [CircuitBreaker(f"c{i}", limit_bytes=30_000, parent=parent)
                for i in range(2)]
    accepted = [0, 0]
    refused = [0]
    lock = threading.Lock()
    barrier = threading.Barrier(8)

    def worker(ci):
        barrier.wait(timeout=10)
        for _ in range(200):
            try:
                children[ci].add_estimate_bytes_and_maybe_break(100, "w")
                with lock:
                    accepted[ci] += 100
            except CircuitBreakingError:
                with lock:
                    refused[0] += 1

    threads = [threading.Thread(target=worker, args=(i % 2,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert children[0].used_bytes == accepted[0]
    assert children[1].used_bytes == accepted[1]
    assert parent.used_bytes == accepted[0] + accepted[1]
    # 8 threads x 200 x 100b = 160k attempted >> 50k parent limit
    assert refused[0] > 0
    assert parent.used_bytes <= parent.limit_bytes
    total_trips = (parent.trip_count + children[0].trip_count
                   + children[1].trip_count)
    assert total_trips == refused[0]
    for ci in range(2):
        children[ci].release(accepted[ci])
    assert parent.used_bytes == 0

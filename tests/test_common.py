import pytest

from elasticsearch_tpu.common import (
    CircuitBreaker,
    CircuitBreakingError,
    ClusterSettings,
    HierarchyCircuitBreakerService,
    IllegalArgumentError,
    Setting,
    Settings,
)
from elasticsearch_tpu.common.settings import parse_bytes_value, parse_time_value


def test_settings_flatten_and_nested_roundtrip():
    s = Settings({"index": {"number_of_shards": 4, "refresh_interval": "1s"}, "cluster.name": "x"})
    assert s.raw("index.number_of_shards") == 4
    assert s.raw("cluster.name") == "x"
    nested = s.as_nested_dict()
    assert nested["index"]["number_of_shards"] == 4


def test_settings_updates_and_null_reset():
    s = Settings({"a.b": 1, "a.c": 2})
    s2 = s.with_updates({"a.b": 5, "a.c": None})
    assert s2.raw("a.b") == 5
    assert s2.raw("a.c") is None
    assert s.raw("a.b") == 1  # immutable


def test_typed_settings():
    num_shards = Setting.int_setting("index.number_of_shards", 1, min_value=1, scope="index")
    refresh = Setting.time_setting("index.refresh_interval", "1s", dynamic=True)
    s = Settings({"index.number_of_shards": "4"})
    assert num_shards.get(s) == 4
    assert refresh.get(s) == 1.0
    assert refresh.get(Settings({"index.refresh_interval": "500ms"})) == 0.5


def test_time_and_bytes_parsing():
    assert parse_time_value("30s") == 30.0
    assert parse_time_value("2m") == 120.0
    assert parse_time_value("100ms") == 0.1
    assert parse_bytes_value("1kb") == 1024
    assert parse_bytes_value("2gb") == 2 << 30
    with pytest.raises(IllegalArgumentError):
        parse_time_value("abc")


def test_cluster_settings_dynamic_update_and_consumer():
    refresh = Setting.time_setting("index.refresh_interval", "1s", dynamic=True)
    static = Setting.int_setting("node.processors", 4)
    cs = ClusterSettings(Settings(), [refresh, static])
    seen = []
    cs.add_settings_update_consumer(refresh, seen.append)
    cs.apply({"index.refresh_interval": "5s"})
    assert seen == [5.0]
    with pytest.raises(IllegalArgumentError):
        cs.apply({"node.processors": 8})  # not dynamic
    with pytest.raises(IllegalArgumentError):
        cs.apply({"nope.unknown": 1})  # unregistered


def test_circuit_breaker_trips_and_releases():
    b = CircuitBreaker("request", limit_bytes=1000)
    b.add_estimate_bytes_and_maybe_break(800, "agg")
    with pytest.raises(CircuitBreakingError):
        b.add_estimate_bytes_and_maybe_break(300, "agg2")
    assert b.used_bytes == 800
    assert b.trip_count == 1
    b.release(800)
    assert b.used_bytes == 0
    b.add_estimate_bytes_and_maybe_break(900, "ok")


def test_hierarchy_breaker_parent_enforced():
    svc = HierarchyCircuitBreakerService(total_limit_bytes=1000)
    req = svc.get_breaker("request")
    fd = svc.get_breaker("fielddata")
    req.add_estimate_bytes_and_maybe_break(500, "r")
    with pytest.raises(CircuitBreakingError):
        fd.add_estimate_bytes_and_maybe_break(390, "f")  # fielddata limit 400, overhead 1.03
    # parent trips even when the child alone would allow it
    with pytest.raises(CircuitBreakingError):
        req.add_estimate_bytes_and_maybe_break(501, "r2")
    assert svc.get_breaker("request").used_bytes == 500

"""fuzzy, regexp, match_phrase_prefix, geo_point queries (VERDICT r2 #9)."""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.search.executor import expand_fuzzy, within_edits


def test_within_edits():
    assert within_edits("search", "search", 0)
    assert within_edits("search", "saerch", 1)      # transposition = 1 edit
    assert within_edits("search", "serch", 1)       # deletion
    assert within_edits("search", "searchh", 1)     # insertion
    assert within_edits("search", "sxarch", 1)      # substitution
    assert not within_edits("search", "sxxrch", 1)
    assert within_edits("search", "sxxrch", 2)
    assert not within_edits("abc", "xyz", 2)
    assert not within_edits("abcdef", "abc", 2)


def test_expand_fuzzy_ordering_and_prefix():
    # dictionaries are segment term dicts: always sorted (bisect prefix range)
    d = sorted(["apple", "apply", "ample", "apples", "banana", "applesauce"])
    out = expand_fuzzy(d, "apple", 2, 0, 10)
    assert out[0] == "apple"                         # exact first
    assert set(out) >= {"apple", "apply", "ample", "apples"}
    assert "banana" not in out and "applesauce" not in out
    out = expand_fuzzy(d, "apple", 2, 2, 10)         # prefix 'ap' required
    assert "ample" not in out


@pytest.fixture(scope="module")
def svc():
    meta = IndexMetadata(index="b", uuid="u", settings=Settings({}), mappings={
        "properties": {
            "body": {"type": "text"},
            "loc": {"type": "geo_point"},
        }})
    svc = IndexService(meta)
    docs = [
        {"body": "the quick brown fox", "loc": {"lat": 52.52, "lon": 13.40}},   # berlin
        {"body": "quack brown duck", "loc": "48.85,2.35"},                       # paris
        {"body": "quicker than lightning", "loc": [-0.12, 51.50]},               # london ([lon, lat])
        {"body": "a slow red fox", "loc": {"lat": 40.71, "lon": -74.00}},        # nyc
        {"body": "quantum leap", "loc": {"lat": 52.40, "lon": 13.05}},           # potsdam
    ]
    for i, d in enumerate(docs):
        svc.index_doc(str(i), d)
    svc.refresh()
    yield svc
    svc.close()


def test_fuzzy_query(svc):
    r = svc.search({"query": {"fuzzy": {"body": {"value": "quick"}}}})
    ids = {h["_id"] for h in r["hits"]["hits"]}
    assert "0" in ids          # quick (d=0)
    assert "1" in ids          # quack (d=1)
    assert "4" not in ids      # quantum (d>2)
    r0 = svc.search({"query": {"fuzzy": {"body": {"value": "quick",
                                                  "fuzziness": 0}}}})
    assert {h["_id"] for h in r0["hits"]["hits"]} == {"0"}


def test_regexp_query(svc):
    r = svc.search({"query": {"regexp": {"body": {"value": "qu.*k"}}}})
    ids = {h["_id"] for h in r["hits"]["hits"]}
    assert ids == {"0", "1"}   # quick, quack (anchored full match)


def test_match_phrase_prefix(svc):
    r = svc.search({"query": {"match_phrase_prefix": {"body": "quick bro"}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["0"]
    r = svc.search({"query": {"match_phrase_prefix": {"body": "slow red fo"}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["3"]
    r = svc.search({"query": {"match_phrase_prefix": {"body": "brown elephant"}}})
    assert r["hits"]["hits"] == []


def test_geo_distance(svc):
    # 50km around berlin: berlin + potsdam
    r = svc.search({"query": {"geo_distance": {
        "distance": "50km", "loc": {"lat": 52.52, "lon": 13.40}}}})
    assert {h["_id"] for h in r["hits"]["hits"]} == {"0", "4"}
    # 1200km: adds paris + london
    r = svc.search({"query": {"geo_distance": {
        "distance": "1200km", "loc": "52.52,13.40"}}})
    assert {h["_id"] for h in r["hits"]["hits"]} == {"0", "1", "2", "4"}


def test_geo_bounding_box(svc):
    r = svc.search({"query": {"geo_bounding_box": {"loc": {
        "top_left": {"lat": 55.0, "lon": -1.0},
        "bottom_right": {"lat": 45.0, "lon": 15.0}}}}})
    assert {h["_id"] for h in r["hits"]["hits"]} == {"0", "1", "2", "4"}
    # narrow box around only berlin/potsdam
    r = svc.search({"query": {"geo_bounding_box": {"loc": {
        "top_left": {"lat": 53.0, "lon": 12.0},
        "bottom_right": {"lat": 52.0, "lon": 14.0}}}}})
    assert {h["_id"] for h in r["hits"]["hits"]} == {"0", "4"}


def test_geo_in_bool_filter(svc):
    r = svc.search({"query": {"bool": {
        "must": [{"match": {"body": "fox"}}],
        "filter": [{"geo_distance": {"distance": "100km",
                                     "loc": {"lat": 52.5, "lon": 13.4}}}]}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["0"]


def test_fuzzy_highlight(svc):
    r = svc.search({"query": {"fuzzy": {"body": "quick"}},
                    "highlight": {"fields": {"body": {}}}})
    by_id = {h["_id"]: h for h in r["hits"]["hits"]}
    assert "<em>quack</em>" in by_id["1"]["highlight"]["body"][0]


def test_multivalued_geo_keeps_pairing():
    """Review r3 finding: per-axis sorted columns scrambled lat/lon pairs.
    The paired GeoColumn must match only the doc's ACTUAL points."""
    meta = IndexMetadata(index="mv", uuid="u", settings=Settings({}), mappings={
        "properties": {"loc": {"type": "geo_point"}}})
    svc = IndexService(meta)
    svc.index_doc("1", {"loc": [{"lat": 10.0, "lon": 50.0},
                                {"lat": 20.0, "lon": 40.0}]})
    svc.refresh()
    # the scrambled cross-pair (10, 40) must NOT match
    r = svc.search({"query": {"geo_distance": {
        "distance": "10km", "loc": {"lat": 10.0, "lon": 40.0}}}})
    assert r["hits"]["hits"] == []
    # both real points match
    for lat, lon in ((10.0, 50.0), (20.0, 40.0)):
        r = svc.search({"query": {"geo_distance": {
            "distance": "10km", "loc": {"lat": lat, "lon": lon}}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["1"], (lat, lon)
    # bounding box around a cross-pair must not match either
    r = svc.search({"query": {"geo_bounding_box": {"loc": {
        "top_left": {"lat": 11.0, "lon": 39.0},
        "bottom_right": {"lat": 9.0, "lon": 41.0}}}}})
    assert r["hits"]["hits"] == []
    svc.close()

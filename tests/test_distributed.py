"""The distributed spine: cluster state drives shards; search/bulk cross the
transport; failover promotes and resyncs — the round-3 "wire the spine"
acceptance tests (VERDICT r2 #1), run on the deterministic in-process
harness (LocalNodeChannels + LocalStateStore)."""

import numpy as np
import pytest

from elasticsearch_tpu.cluster_node import form_local_cluster

MAPPINGS = {"properties": {"n": {"type": "integer"},
                           "body": {"type": "text"}}}


def make_cluster(n_data=3, data_path=None):
    """Dedicated master m0 + n data nodes (victim-safe failover tests)."""
    names = ["m0"] + [f"d{i}" for i in range(n_data)]
    roles = {"m0": ("master",)}
    return form_local_cluster(names, data_path=data_path, roles=roles)


def index_body(shards=2, replicas=1):
    return {"settings": {"number_of_shards": shards,
                         "number_of_replicas": replicas},
            "mappings": MAPPINGS}


def bulk_ops(start, count):
    return [{"op": "index", "id": str(i),
             "source": {"n": i, "body": f"word{i % 7} common text"}}
            for i in range(start, start + count)]


def test_create_index_allocates_and_goes_green():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    health = a.health()
    assert health["status"] == "green"
    assert health["active_shards"] == 4
    state = store.current()
    # same-shard rule: primary and replica of one shard on different nodes
    for sid in range(2):
        copies = state.shard_copies("docs", sid)
        assert len({r.node_id for r in copies}) == len(copies)
        assert all(r.state == "STARTED" for r in copies)
    # in-sync set contains every started copy
    meta = state.indices["docs"]
    for sid in range(2):
        assert len(meta.in_sync_allocations[sid]) == 2


def test_bulk_via_one_node_search_via_another():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    resp = a.bulk("docs", bulk_ops(0, 50))
    assert not resp["errors"]
    assert all(r["_seq_no"] >= 0 for r in resp["items"])
    a.refresh("docs")
    r = b.search("docs", {"query": {"match": {"body": "common"}},
                          "size": 10, "track_total_hits": True})
    assert r["hits"]["total"]["value"] == 50
    assert len(r["hits"]["hits"]) == 10
    assert r["_shards"]["failed"] == 0
    # a term query via the third node agrees
    r2 = c.search("docs", {"query": {"match": {"body": "word3"}},
                           "size": 20})
    expect = len([i for i in range(50) if i % 7 == 3])
    assert r2["hits"]["total"]["value"] == expect


def test_replicas_serve_identical_data():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(1, 2))
    a.bulk("docs", bulk_ops(0, 30))
    a.refresh("docs")
    state = store.current()
    copies = state.shard_copies("docs", 0)
    assert len(copies) == 3
    # every copy holds the same docs
    counts = set()
    for r in copies:
        node = next(n for n in nodes if n.node_name == r.node_id)
        inst = node.shard_service.get_shard("docs", 0)
        counts.add(inst.engine.doc_count())
    assert counts == {30}


def test_primary_failover_promotes_and_writes_continue():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    state = store.current()
    victim_name = state.primary_of("docs", 0).node_id
    old_term = state.indices["docs"].primary_term(0)
    victim = next(n for n in nodes if n.node_name == victim_name)
    survivors = [n for n in nodes[1:] if n.node_name != victim_name]

    channels.kill(victim_name)
    store.remove_applier(victim_name)
    survivors[0].report_node_left(victim_name)

    state = store.current()
    new_primary = state.primary_of("docs", 0)
    assert new_primary is not None and new_primary.state == "STARTED"
    assert new_primary.node_id != victim_name
    assert state.indices["docs"].primary_term(0) == old_term + 1
    assert victim_name not in state.nodes

    # writes keep flowing through the promoted primary
    resp = survivors[0].bulk("docs", bulk_ops(40, 20))
    assert not resp["errors"]
    survivors[0].refresh("docs")
    r = survivors[1].search("docs", {"query": {"match_all": {}},
                                     "track_total_hits": True, "size": 0})
    assert r["hits"]["total"]["value"] == 60


def test_failover_discards_divergent_unacked_write():
    """A write the dead primary never fully replicated must not survive on
    the promoted side once resync runs (ref: PrimaryReplicaSyncer)."""
    nodes, store, channels = make_cluster(n_data=2)
    master, a, b = nodes
    a.create_index("docs", index_body(1, 1))
    a.bulk("docs", bulk_ops(0, 10))

    state = store.current()
    primary_r = state.primary_of("docs", 0)
    primary_node = next(n for n in nodes if n.node_name == primary_r.node_id)
    replica_node = next(n for n in nodes[1:]
                        if n.node_name != primary_r.node_id)

    # simulate divergence: op lands on the primary engine only (replication
    # suppressed), as when the primary dies mid-fan-out
    inst = primary_node.shard_service.get_shard("docs", 0)
    with inst.lock:
        inst.engine.index("divergent", {"n": 999, "body": "ghost"})

    channels.kill(primary_r.node_id)
    store.remove_applier(primary_r.node_id)
    replica_node.report_node_left(primary_r.node_id)

    new_inst = replica_node.shard_service.get_shard("docs", 0)
    assert new_inst.primary
    assert new_inst.engine.get("divergent") is None
    # acked writes all survive
    for i in range(10):
        assert new_inst.engine.get(str(i)) is not None


def test_new_node_receives_replica_via_peer_recovery():
    """VERDICT r2 #4 acceptance: a later-added replica bootstraps over the
    recovery protocol and serves identical results."""
    nodes, store, channels = make_cluster(n_data=2)
    master, a, b = nodes
    a.create_index("docs", index_body(1, 1))
    a.bulk("docs", bulk_ops(0, 200))
    a.delete_index_docs = None  # readability no-op
    # delete some docs so live masks transfer too
    del_ops = [{"op": "delete", "id": str(i)} for i in range(0, 200, 10)]
    a.bulk("docs", del_ops)
    a.refresh("docs")

    state = store.current()
    copies = state.shard_copies("docs", 0)
    per_copy = set()
    for r in copies:
        node = next(n for n in nodes if n.node_name == r.node_id)
        eng = node.shard_service.get_shard("docs", 0).engine
        per_copy.add(eng.doc_count())
        assert eng.get("5") is not None
        assert eng.get("10") is None
    assert per_copy == {180}

    # both copies answer the same query identically
    r1 = a.search("docs", {"query": {"match": {"body": "word3"}},
                           "size": 200})
    ids1 = sorted(h["_id"] for h in r1["hits"]["hits"])
    r2 = b.search("docs", {"query": {"match": {"body": "word3"}},
                           "size": 200})
    assert sorted(h["_id"] for h in r2["hits"]["hits"]) == ids1


def test_concurrent_style_writes_during_recovery_converge():
    """Writes interleaved with recovery phases reach the new copy exactly
    once (seqno idempotency)."""
    nodes, store, channels = make_cluster(n_data=2)
    master, a, b = nodes
    a.create_index("docs", index_body(1, 0))
    a.bulk("docs", bulk_ops(0, 50))

    # raise replica count -> reroute assigns -> recovery runs; inject a
    # write between prepare and finalize via the channels fault hook
    state = store.current()
    primary_r = state.primary_of("docs", 0)
    primary_node = next(n for n in nodes if n.node_name == primary_r.node_id)

    injected = {"done": False}

    def fault(node, action):
        if action == "internal:index/shard/recovery/ops" \
                and not injected["done"]:
            injected["done"] = True
            primary_node.bulk("docs", bulk_ops(50, 5))

    channels.fault_hook = fault

    def add_replica(st):
        from elasticsearch_tpu.cluster.state import ShardRouting

        entries = list(st.routing["docs"])
        entries.append(ShardRouting(index="docs", shard_id=0, node_id=None,
                                    primary=False, state="UNASSIGNED"))
        st = st.with_routing_updates("docs", entries)
        return primary_node.allocation.reroute(st)

    store.submit(add_replica)
    channels.fault_hook = None

    assert injected["done"], "fault hook never fired"
    state = store.current()
    copies = state.shard_copies("docs", 0)
    assert len(copies) == 2
    assert all(r.state == "STARTED" for r in copies)
    for r in copies:
        node = next(n for n in nodes if n.node_name == r.node_id)
        eng = node.shard_service.get_shard("docs", 0).engine
        assert eng.doc_count() == 55


def test_aggregations_reduce_across_nodes():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 0))
    a.bulk("docs", bulk_ops(0, 60))
    a.refresh("docs")
    r = b.search("docs", {
        "size": 0,
        "aggs": {"mx": {"max": {"field": "n"}},
                 "avg_n": {"avg": {"field": "n"}}}})
    assert r["aggregations"]["mx"]["value"] == 59
    assert abs(r["aggregations"]["avg_n"]["value"] - 29.5) < 1e-9


def test_sorted_search_across_nodes():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(3, 0))
    a.bulk("docs", bulk_ops(0, 45))
    a.refresh("docs")
    r = a.search("docs", {"sort": [{"n": {"order": "desc"}}], "size": 5})
    assert [h["_source"]["n"] for h in r["hits"]["hits"]] == [44, 43, 42, 41, 40]


def test_interrupted_recovery_retries_cleanly():
    """VERDICT r2 #4: an interrupted recovery must fail the copy, and the
    re-allocated attempt must complete from scratch (pull protocol is
    idempotent)."""
    nodes, store, channels = make_cluster(n_data=2)
    master, a, b = nodes
    a.create_index("docs", index_body(1, 0))
    a.bulk("docs", bulk_ops(0, 80))

    from elasticsearch_tpu.transport.channels import NodeUnavailableError

    fail_once = {"armed": True}

    def fault(node, action):
        if action == "internal:index/shard/recovery/segments" \
                and fail_once["armed"]:
            fail_once["armed"] = False
            raise NodeUnavailableError("injected: transfer interrupted")

    channels.fault_hook = fault

    def add_replica(st):
        from elasticsearch_tpu.cluster.state import ShardRouting

        entries = list(st.routing["docs"])
        entries.append(ShardRouting(index="docs", shard_id=0, node_id=None,
                                    primary=False, state="UNASSIGNED"))
        return a.allocation.reroute(st.with_routing_updates("docs", entries))

    store.submit(add_replica)
    channels.fault_hook = None
    assert not fail_once["armed"], "fault never fired"

    state = store.current()
    copies = state.shard_copies("docs", 0)
    # first attempt failed -> shard-failed -> reroute -> second attempt green
    assert len(copies) == 2
    assert all(r.state == "STARTED" for r in copies)
    for r in copies:
        node = next(n for n in nodes if n.node_name == r.node_id)
        assert node.shard_service.get_shard("docs", 0).engine.doc_count() == 80


def test_peer_recovery_at_scale_100k_docs():
    """VERDICT r2 #4 scale bar: a new replica of a 100k-doc shard bootstraps
    over the recovery protocol and serves identical counts."""
    nodes, store, channels = make_cluster(n_data=2)
    master, a, b = nodes
    a.create_index("docs", index_body(1, 0))
    for start in range(0, 100_000, 10_000):
        a.bulk("docs", bulk_ops(start, 10_000))

    def add_replica(st):
        from elasticsearch_tpu.cluster.state import ShardRouting

        entries = list(st.routing["docs"])
        entries.append(ShardRouting(index="docs", shard_id=0, node_id=None,
                                    primary=False, state="UNASSIGNED"))
        return a.allocation.reroute(st.with_routing_updates("docs", entries))

    store.submit(add_replica)
    state = store.current()
    copies = state.shard_copies("docs", 0)
    assert len(copies) == 2 and all(r.state == "STARTED" for r in copies)
    for r in copies:
        node = next(n for n in nodes if n.node_name == r.node_id)
        assert node.shard_service.get_shard("docs", 0).engine.doc_count() \
            == 100_000


def test_can_match_skips_shards_without_required_terms():
    """Coordinator pre-filter (ref CanMatchPreFilterSearchPhase): shards
    provably holding no copy of a required term are skipped."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(4, 0))
    # route a unique term to whichever shard doc "special" hashes to
    a.bulk("docs", [{"op": "index", "id": "special",
                     "source": {"n": 1, "body": "uniqueterm only here"}}]
           + bulk_ops(0, 40))
    a.refresh("docs")
    r = b.search("docs", {"query": {"term": {"body": "uniqueterm"}},
                          "track_total_hits": True})
    assert r["hits"]["total"]["value"] == 1
    assert r["_shards"]["skipped"] >= 1
    assert r["_shards"]["successful"] == r["_shards"]["total"]
    # a term present everywhere skips nothing
    r2 = b.search("docs", {"query": {"term": {"body": "common"}},
                           "track_total_hits": True})
    assert r2["_shards"]["skipped"] == 0
    assert r2["hits"]["total"]["value"] == 40


def test_adaptive_replica_selection_updates_ewma():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 30))
    a.refresh("docs")
    # search from the master (no local copies): remote selection by EWMA
    svc = master.search_action
    for _ in range(3):
        master.search("docs", {"query": {"match": {"body": "common"}}})
    assert svc._node_ewma_ms, "EWMA stats must accumulate"
    assert all(v >= 0 for v in svc._node_ewma_ms.values())


def test_distributed_profile_returns_tree():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 0))
    a.bulk("docs", bulk_ops(0, 30))
    a.refresh("docs")
    r = b.search("docs", {"query": {"match": {"body": "common"}},
                          "profile": True})
    shards = r["profile"]["shards"]
    assert len(shards) == 2
    q = shards[0]["searches"][0]["query"]
    assert q and q[0]["type"] == "MatchQuery"
    assert q[0]["time_in_nanos"] > 0


def test_shard_serving_fast_path_matches_dense(monkeypatch):
    """VERDICT r4 item 10: the flagship serving engines compose with the
    mesh THROUGH the transport scatter-gather — each data node answers the
    shard query phase on its Turbo/BlockMax engine. Bit-identical with the
    dense executor (same shard-local stats), fetch/reduce unchanged."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(3, 0))
    a.bulk("docs", bulk_ops(0, 120))
    a.refresh("docs")

    bodies = [
        {"query": {"match": {"body": "common"}}, "size": 15,
         "track_total_hits": True},
        {"query": {"match": {"body": "word1 word4"}}, "size": 25},
        {"query": {"term": {"body": "word2"}}, "size": 10, "from": 3},
    ]
    for body in bodies:
        fast = b.search("docs", body)
        monkeypatch.setenv("ES_TPU_DISABLE_SHARD_SERVING", "1")
        dense = c.search("docs", body)
        monkeypatch.delenv("ES_TPU_DISABLE_SHARD_SERVING")
        assert [h["_id"] for h in fast["hits"]["hits"]] == \
            [h["_id"] for h in dense["hits"]["hits"]], body
        for x, y in zip(fast["hits"]["hits"], dense["hits"]["hits"]):
            assert abs(x["_score"] - y["_score"]) < 1e-5
        assert fast["hits"]["total"] == dense["hits"]["total"]

"""ICI-sharded TurboBM25 differential suite (PR 4).

With S > 1 partitions on a multi-device mesh, TurboEngine serves every
partition's sweep as ONE fused shard_map dispatch and merges the
per-partition top-ks ON DEVICE (parallel.spmd.merge_partition_topk).
The host route — solo per-partition search_many + TurboEngine._merge3 —
is the reference, and the contract is BIT-identity: merging permutes
the exact per-partition f32 scores, it never recomputes them, so the
two routes must agree to the last bit including the (score desc,
partition asc, ord asc) tie-break.

Runs on the host-simulated 8-device CPU mesh from tests/conftest.py
(Pallas kernels interpret on CPU); the multidevice marker documents the
lane — these tests ARE tier-1.
"""

import threading

import numpy as np
import pytest

from elasticsearch_tpu.index.segment import build_field_postings
from elasticsearch_tpu.parallel.spmd import build_stacked_bm25
from elasticsearch_tpu.parallel.turbo import TurboBM25

pytestmark = pytest.mark.multidevice


class _Seg:
    def __init__(self, n_docs, fp):
        self.n_docs = n_docs
        self.postings = {"body": fp}
        self.vectors = {}


def _pcorpus(n_docs, vocab, seed):
    """Positional Zipf corpus (token_pos = in-doc offset, so adjacent
    pairs are real slop-0 phrase hits)."""
    rng = np.random.default_rng(seed)
    probs = 1.0 / np.arange(1, vocab + 1) ** 1.1
    probs /= probs.sum()
    lens = rng.integers(4, 24, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum()), p=probs).astype(np.int64)
    return _corpus_fp(lens, tokens, vocab)


def _corpus_fp(lens, tokens, vocab):
    n_docs = len(lens)
    tok_docs = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    bounds = np.concatenate([[0], np.cumsum(lens)])
    tok_pos = (np.arange(len(tokens), dtype=np.int64)
               - np.repeat(bounds[:-1], lens))
    names = [f"t{i}" for i in range(vocab)]
    return build_field_postings("body", lens, tok_docs, tokens, names,
                                token_pos=tok_pos)


def _turbo(fp, n_docs, cold_df=5, hbm=64 << 20, **kw):
    stacked = build_stacked_bm25([_Seg(n_docs, fp)], "body", serve_only=True)
    return TurboBM25(stacked, hbm_budget_bytes=hbm, cold_df=cold_df, **kw)


def _fused_engine(parts, cold_df=5, **kw):
    """TurboEngine over S partitions WITH the fused mesh, as
    select_bm25_engine builds it for S > 1."""
    from elasticsearch_tpu.search.serving import TurboEngine, _turbo_mesh

    turbos = [_turbo(fp, n, cold_df=cold_df, **kw) for n, fp in parts]
    return TurboEngine(turbos, mesh=_turbo_mesh(len(turbos)))


@pytest.fixture(scope="module")
def eng3():
    """Three partitions of different sizes AND vocabularies — different
    slot counts (Hp) per partition exercise the weight-axis padding in
    the fused dispatch, and terms absent from the small-vocab partition
    exercise partial term presence."""
    return _fused_engine([(1500, _pcorpus(1500, 40, 1)),
                          (900, _pcorpus(900, 56, 2)),
                          (2100, _pcorpus(2100, 32, 3))])


def _assert_rows_equal(got, want, ctx):
    for g, w, name in zip(got, want, ("scores", "parts", "ords")):
        assert np.array_equal(np.asarray(g), np.asarray(w)), (ctx, name)


def _host_route_many(eng, batch, k):
    per = [t.search_many([batch], k=k)[0] for t in eng.turbos]
    return eng._merge3(per, len(batch), k)


def _host_route_bool(eng, specs, k):
    per = [t.search_bool(specs, k=k) for t in eng.turbos]
    return eng._merge3(per, len(specs), k)


# ---------------------------------------------------------------------------
# the merge kernel against an independent lexicographic reference
# ---------------------------------------------------------------------------


def _ref_merge(scores, ords, k):
    Q, L = scores.shape
    out = (np.zeros((Q, k), np.float32), np.zeros((Q, k), np.int32),
           np.zeros((Q, k), np.int32))
    for qi in range(Q):
        cand = [(float(s), lane // k, int(o))
                for lane, (s, o) in enumerate(zip(scores[qi], ords[qi]))
                if s > 0]
        cand.sort(key=lambda x: (-x[0], x[1], x[2]))
        for j, (s, p, o) in enumerate(cand[:k]):
            out[0][qi, j], out[1][qi, j], out[2][qi, j] = s, p, o
    return out


def test_merge_topk_matches_lexicographic_reference():
    from elasticsearch_tpu.parallel.kernels import merge_topk

    rng = np.random.default_rng(5)
    Q, S, k = 6, 4, 10
    # few distinct score values force heavy cross-partition ties; ords
    # unique per partition lane block (real partitions emit distinct docs)
    scores = rng.choice(np.asarray([0.0, 0.0, 1.5, 2.25, 3.5], np.float32),
                        size=(Q, S * k))
    ords = np.stack([rng.permutation(1000)[:S * k] for _ in range(Q)])
    ords = ords.astype(np.int32)
    got = merge_topk(scores, ords, k=k)
    _assert_rows_equal(got, _ref_merge(scores, ords, k), "merge_topk")


# ---------------------------------------------------------------------------
# fused dispatch + device merge vs solo + host _merge3
# ---------------------------------------------------------------------------


def test_fused_disjunctive_bit_identical_one_dispatch(eng3):
    batch = [["t0", "t1"], ["t3"], [("t2", 2.0), "t5"], ["t7", "t0", "t9"],
             ["t33", "t1"],        # t33 absent from the vocab-32 partition
             ["t90"]]              # absent from EVERY partition
    d0 = {id(t): t.stats["dispatches"] for t in eng3.turbos}
    f0 = eng3.merge_stats["fused_dispatches"]
    m0 = eng3.merge_stats["merge_device"]
    got = eng3.search_many([batch], k=10)[0]
    # one ≤8-query batch -> exactly ONE fused dispatch for all S
    # partitions, merged on device; no per-partition solo dispatches
    assert eng3.merge_stats["fused_dispatches"] - f0 == 1
    assert eng3.merge_stats["merge_device"] - m0 == 1
    assert all(t.stats["dispatches"] == d0[id(t)] for t in eng3.turbos)
    _assert_rows_equal(got, _host_route_many(eng3, batch, 10), "disj")


def test_fused_multi_batch_and_chunking():
    # a single compiled width of 8: the 9-query flat batch (both caller
    # batches aggregate into one flat dispatch stream) splits into two
    # 8-wide chunks -> two fused dispatches, each covering ALL
    # partitions, and still one device merge per caller batch
    eng = _fused_engine([(500, _pcorpus(500, 30, 61)),
                         (400, _pcorpus(400, 30, 67))], qc_sizes=(8,))
    b1 = [[f"t{i}", f"t{(i * 3 + 1) % 20}"] for i in range(7)]
    b2 = [["t2"], ["t4", "t6"]]
    f0 = eng.merge_stats["fused_dispatches"]
    m0 = eng.merge_stats["merge_device"]
    got = eng.search_many([b1, b2], k=7)
    assert eng.merge_stats["fused_dispatches"] - f0 == 2
    assert eng.merge_stats["merge_device"] - m0 == 2
    _assert_rows_equal(got[0], _host_route_many(eng, b1, 7), "b1")
    _assert_rows_equal(got[1], _host_route_many(eng, b2, 7), "b2")


def test_fused_bool_and_phrase_bit_identical(eng3):
    specs = [
        {"must": [("t0", 1.0), ("t1", 1.0)]},
        {"must": [("t2", 1.0)], "must_not": ["t1"]},
        {"should": [("t3", 1.0), ("t4", 2.0)]},
        {"must": [("t0", 1.0)], "filter": ["t5"]},
        {"must": [("t0", 1.0)], "phrases": [(("t0", "t1"), 0, 1.0)]},
        {"phrases": [(["t1", "t0"], 0, 1.0)]},
    ]
    got = eng3.search_bool(specs, k=10)
    _assert_rows_equal(got, _host_route_bool(eng3, specs, 10), "bool")

    phrases = [["t0", "t1"], ["t2", "t0"], ["t1", "t3"]]
    got_p = eng3.search_phrase(phrases, k=5, slop=0)
    per = [t.search_phrase(phrases, k=5, slop=0) for t in eng3.turbos]
    _assert_rows_equal(got_p, eng3._merge3(per, len(phrases), 5), "phrase")


def test_fused_refresh_picks_up_new_columns(eng3):
    """Columns built AFTER the ShardedTurbo uploaded (cols_epoch bump)
    must be re-uploaded before the next fused dispatch."""
    epochs0 = [t.cols_epoch for t in eng3.turbos]
    batch = [["t11", "t13"], ["t12", "t14", "t15"]]
    got = eng3.search_many([batch], k=10)[0]
    _assert_rows_equal(got, _host_route_many(eng3, batch, 10), "refresh")
    # the differential itself is the real check; the epochs moving shows
    # this test actually exercised the refresh path at least once overall
    assert all(t.cols_epoch >= e for t, e in zip(eng3.turbos, epochs0))


def test_fused_certificate_fallback_bit_identical(eng3):
    """force_cert_fail (the bool-path certificate test hook) discards
    the device collection inside the fused path too — the per-partition
    exact host fallback runs and the merge still agrees with the solo
    route (both exact)."""
    specs = [{"must": [("t0", 1.0), ("t6", 1.0)]},
             {"must": [("t1", 1.0)], "should": [("t2", 1.0)]}]
    fb0 = eng3.stats["fallbacks"]
    try:
        for t in eng3.turbos:
            t.force_cert_fail = True
        got = eng3.search_bool(specs, k=10)
        want = _host_route_bool(eng3, specs, 10)
    finally:
        for t in eng3.turbos:
            t.force_cert_fail = False
    _assert_rows_equal(got, want, "cert-fail")
    assert eng3.stats["fallbacks"] > fb0


# ---------------------------------------------------------------------------
# tie-break: equal scores across and within partitions, short partitions
# ---------------------------------------------------------------------------


def test_fused_ties_across_partitions():
    """Two partitions with IDENTICAL corpora: every hit is an exact
    cross-partition score tie; order must be partition asc at equal
    (score, ord) and stay bit-identical to _merge3."""
    fp = _pcorpus(700, 30, 7)
    eng = _fused_engine([(700, fp), (700, fp)])
    batch = [["t0", "t2"], ["t1"], ["t4", "t5"]]
    got = eng.search_many([batch], k=10)[0]
    _assert_rows_equal(got, _host_route_many(eng, batch, 10), "xpart ties")
    s, p, o = got
    for qi in range(len(batch)):
        for j in range(9):
            if s[qi, j] > 0 and s[qi, j] == s[qi, j + 1]:
                assert (p[qi, j], o[qi, j]) < (p[qi, j + 1], o[qi, j + 1])


def test_fused_ties_within_partition():
    """A partition whose second half duplicates its first half: equal
    (score, partition) pairs must order by ord asc."""
    rng = np.random.default_rng(17)
    lens = rng.integers(4, 20, size=400).astype(np.int64)
    toks = rng.choice(25, size=int(lens.sum()),
                      p=(lambda w: w / w.sum())(
                          1.0 / np.arange(1, 26) ** 1.1)).astype(np.int64)
    fp_dup = _corpus_fp(np.concatenate([lens, lens]),
                        np.concatenate([toks, toks]), 25)
    eng = _fused_engine([(800, fp_dup), (600, _pcorpus(600, 25, 19))])
    batch = [["t0", "t1"], ["t3", "t2"]]
    got = eng.search_many([batch], k=10)[0]
    _assert_rows_equal(got, _host_route_many(eng, batch, 10), "inpart ties")


def test_fused_k_exceeds_partition_candidates():
    """A tail term matching only a handful of docs per partition: some
    partitions contribute fewer than k candidates, the merged tail pads
    with (0, 0, 0) exactly as _merge3 does."""
    eng = _fused_engine([(60, _pcorpus(60, 40, 23)),
                         (40, _pcorpus(40, 40, 29)),
                         (50, _pcorpus(50, 40, 31))], cold_df=2)
    batch = [["t38"], ["t39", "t37"], ["t36"]]
    got = eng.search_many([batch], k=10)[0]
    want = _host_route_many(eng, batch, 10)
    _assert_rows_equal(got, want, "short partitions")
    assert np.any(got[0] == 0), "expected padded tail slots"


# ---------------------------------------------------------------------------
# serving selection + coalescer stability for the sharded engine
# ---------------------------------------------------------------------------


def test_select_engine_routes_multi_partition_to_fused_turbo(monkeypatch):
    from elasticsearch_tpu.search.serving import (select_bm25_engine,
                                                  turbo_eligible)

    monkeypatch.setenv("ES_TPU_FORCE_TURBO", "1")
    segs = [_Seg(600, _pcorpus(600, 30, 41)), _Seg(450, _pcorpus(450, 30, 43))]
    from elasticsearch_tpu.parallel import make_mesh

    mesh = make_mesh(2, dp=1)
    assert turbo_eligible(segs, "body", mesh, cold_df=5)
    eng = select_bm25_engine(segs, "body", None, mesh, cold_df=5)
    assert eng.kind == "turbo"
    assert eng.mesh is not None, "S > 1 must get the fused turbo mesh"
    batch = [["t0", "t1"], ["t2"]]
    got = eng.search_many([batch], k=10)[0]
    _assert_rows_equal(got, _host_route_many(eng, batch, 10), "selected")
    assert eng.merge_stats["merge_device"] >= 1


def test_turbo_mesh_env_disable(monkeypatch):
    from elasticsearch_tpu.search.serving import _turbo_mesh

    assert _turbo_mesh(1) is None          # S == 1 never fuses
    assert _turbo_mesh(3) is not None
    monkeypatch.setenv("ES_TPU_TURBO_MESH", "0")
    assert _turbo_mesh(3) is None          # explicit opt-out
    monkeypatch.setenv("ES_TPU_TURBO_MESH", "2")
    m = _turbo_mesh(5)
    assert m is not None and m.devices.size == 2


def test_sharded_engine_coalescer_rows_and_keys():
    """Satellite 4: the coalescer serves the SHARDED TurboEngine with
    rows bit-identical to solo dispatch, and its batch keying stays
    stable — one serial per engine object, distinct across the engine
    swap a mid-window snapshot refresh performs."""
    from elasticsearch_tpu.threadpool.coalescer import (DispatchCoalescer,
                                                        _engine_key)

    eng = _fused_engine([(600, _pcorpus(600, 30, 47)),
                         (500, _pcorpus(500, 30, 53))])
    queries = [["t0", "t1"], ["t2"], ["t1", "t3"], ["t4"]]
    solo = [eng.search_many([[q]], k=10)[0] for q in queries]

    co = DispatchCoalescer(window_us=400_000, max_batch=len(queries))
    results = [None] * len(queries)
    errors = []
    barrier = threading.Barrier(len(queries))

    def worker(i, q):
        try:
            barrier.wait(timeout=10)
            results[i] = co.dispatch(eng, [q], 10)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i, q))
               for i, q in enumerate(queries)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for q, got, want in zip(queries, results, solo):
        _assert_rows_equal((got[0][0], got[1][0], got[2][0]),
                           (want[0][0], want[1][0], want[2][0]), q)
    assert co.stats()["largest_batch"] > 1        # merging happened

    # keying: stable per object, distinct across objects — a refreshed
    # snapshot's NEW engine (even one landing at the same id() after the
    # old is collected) can never join the old engine's batch
    k1, k1b = _engine_key(eng), _engine_key(eng)
    assert k1 == k1b
    eng2 = type(eng)(eng.turbos, mesh=eng.mesh)   # refreshed wrapper
    assert _engine_key(eng2) != k1
    assert _engine_key(eng2) == _engine_key(eng2)

"""Eager sparse impact slice differential suite (PR 17).

Cold terms (df < COLD_DF) no longer fork to the `_cold_contrib` host walk
on the serving path: at column-upload time each cold query term gets an
eagerly-scored sparse slice — packed ``doc << 8 | impact`` granules with a
per-term uint8 quantization scale — and `kernels.sparse_gather` scatters
them into a dense per-tile accumulator on device. The contract: the device
contribution plus its tracked error bound (`slack`, the cold twin of the
`e_q` certificate arithmetic) is a true upper bound, so the bound-pruned
survivor set is a SUPERSET of the host path's, every survivor is exact
host rescored, and top-k stays BIT-identical to the host reference on
every route — solo, fused S > 1, bool with cold clauses, the host A/B
(`ES_TPU_SPARSE=0`), certificate fallback, injected `sparse_gather`
faults, and an HBM scrub cycle repairing a corrupted slice pool.

Runs on the host-simulated 8-device CPU mesh from tests/conftest.py
(Pallas kernels interpret on CPU)."""

import numpy as np
import pytest

from elasticsearch_tpu.common import faults, integrity
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.parallel.turbo import SPARSE_GRAN, _sparse_widths

from test_turbo_bitset import _pcorpus, _turbo, _fused, _assert_identical

pytestmark = pytest.mark.multidevice

K = 10
# _pcorpus(3000, 40, 7) dfs run ~2886 down to ~173; cold_df=800 leaves
# terms t8.. cold, t0..t7 colized — queries below straddle the boundary
COLD_DF = 800


def _queries():
    qs = [[(f"t{i}", 1.0), (f"t{i + 11}", 0.7)] for i in range(0, 20, 3)]
    qs.append([("t30", 1.0), ("t35", 1.0)])            # cold-only
    qs.append([("t31", 2.0)])                          # single cold term
    qs.append([("t0", 1.0), ("t25", 1.0), ("t38", 0.5)])   # mixed
    qs.append([("t1", 1.0), ("t2", 0.5)])              # colized-only
    qs.append([("absent", 1.0), ("t33", 1.0)])         # unknown + cold
    return qs


def test_sparse_solo_bit_identical():
    t = _turbo(_pcorpus(3000, 40, 7), 3000, cold_df=COLD_DF)
    qs = _queries()
    got = t.search_many([qs], k=K)[0]
    want = t.search_many_host([qs], k=K)[0]
    _assert_identical(got, want, "sparse solo vs host")
    assert t.stats["cold_queries"] == 0, "host cold fork still serving"
    assert t.stats["sparse_queries"] > 0, "sparse route never engaged"
    assert t.stats["sparse_slices"] > 0, "no slices built"
    assert t.stats["sparse_fallbacks"] == 0
    assert t.stats["sparse_bytes"] > 0
    assert t._sp_pool is not None and t._sp_host is not None
    # every resident slice is granule-aligned on a declared ladder rung
    widths = _sparse_widths()
    for g0, n_g, w, sscale in t._sp_of.values():
        assert w in widths and w == n_g * SPARSE_GRAN and sscale > 0


def test_sparse_off_ab_identical(monkeypatch):
    """ES_TPU_SPARSE=0 restores the host cold fork verbatim — same bits,
    today's counters."""
    fp = _pcorpus(3000, 40, 7)
    qs = _queries()
    on = _turbo(fp, 3000, cold_df=COLD_DF)
    got_on = on.search_many([qs], k=K)[0]
    monkeypatch.setenv("ES_TPU_SPARSE", "0")
    off = _turbo(fp, 3000, cold_df=COLD_DF)
    got_off = off.search_many([qs], k=K)[0]
    _assert_identical(got_on, got_off, "sparse on vs off A/B")
    _assert_identical(got_off, off.search_many_host([qs], k=K)[0],
                      "sparse off vs host")
    assert off.stats["cold_queries"] > 0
    assert off.stats["sparse_queries"] == 0
    assert off.stats["sparse_slices"] == 0 and off.stats["sparse_bytes"] == 0
    assert off._sp_pool is None, "slices built despite ES_TPU_SPARSE=0"


def test_sparse_bool_bit_identical():
    """Bool route: cold SHOULD terms score via the sparse tier; cold
    must/must_not clauses keep their exact host routing — all specs stay
    bit-identical to search_bool_host."""
    t = _turbo(_pcorpus(3000, 40, 7), 3000, cold_df=COLD_DF)
    specs = [
        {"must": [("t1", 1.0)], "should": [("t30", 1.0), ("t35", 0.5)]},
        {"must": [("t25", 1.0), ("t3", 1.0)], "must_not": ["t33"]},
        {"filter": ["t4"], "should": [("t38", 1.0)]},
        {"must": [("t2", 1.0)], "should": [("t8", 1.0), ("t31", 1.0)]},
        {"should": [("t28", 1.0), ("t36", 2.0)]},      # all-cold scoring
        {"must": [("t34", 1.0)], "must_not": ["t0"]},  # cold must
    ]
    got = t.search_bool(specs, k=K)
    want = t.search_bool_host(specs, k=K)
    _assert_identical(got, want, "sparse bool vs host")
    assert t.stats["sparse_queries"] > 0, "bool cold side never sparse"
    assert t.stats["cold_queries"] == 0


def test_sparse_fused_bit_identical():
    """S=3 fused dispatch (different sizes, vocabularies, df spectra,
    therefore different per-partition slice pools) against each
    partition's host route, plus the ledger == hbm_bytes cross-check."""
    eng = _fused([(1500, _pcorpus(1500, 40, 1)),
                  (900, _pcorpus(900, 56, 2)),
                  (2100, _pcorpus(2100, 32, 3))], cold_df=300)
    st = eng._fused()
    qs = [[("t1", 1.0), ("t20", 1.0)], [("t25", 1.0), ("t30", 0.5)],
          [("t2", 1.0)], [("t28", 1.0), ("t31", 1.0), ("t3", 0.2)]]
    per = st.search_many([qs], k=K)
    for si, t in enumerate(st.turbos):
        _assert_identical(per[si][0], t.search_many_host([qs], k=K)[0],
                          f"fused partition {si} vs host")
    assert sum(t.stats["sparse_queries"] for t in st.turbos) > 0
    assert all(t.stats["cold_queries"] == 0 for t in st.turbos)
    # ledger cross-check: the slice pool is a ledgered region, and each
    # engine's ledgered occupancy stays byte-identical to hbm_bytes()
    for t in st.turbos:
        assert t._hbm.total_bytes() == t.hbm_bytes()
        if t._sp_pool is not None:
            assert t._sp_pool.nbytes > 0
    assert eng.hbm_bytes() == (sum(t.hbm_bytes() for t in st.turbos)
                               + st.hbm_bytes())


def test_sparse_widths_ladder(monkeypatch):
    """A custom ES_TPU_SPARSE_WIDTHS ladder is honored (rounded up to
    granule multiples) and stays bit-identical; a term above the top rung
    falls back to the exact host walk."""
    monkeypatch.setenv("ES_TPU_SPARSE_WIDTHS", "1024,2048")
    assert _sparse_widths() == (1024, 2048)
    fp = _pcorpus(3000, 40, 7)
    t = _turbo(fp, 3000, cold_df=2500)   # t2 (df~1892) cold, > 1024 rung
    qs = [[("t2", 1.0), ("t30", 1.0)], [("t35", 1.0), ("t38", 1.0)]]
    got = t.search_many([qs], k=K)[0]
    _assert_identical(got, t.search_many_host([qs], k=K)[0],
                      "custom ladder vs host")
    assert all(w in (1024, 2048) for _, _, w, _ in t._sp_of.values())
    # df above the ladder: the whole batch host-falls-back, still counted
    monkeypatch.setenv("ES_TPU_SPARSE_WIDTHS", "1024")
    t2 = _turbo(fp, 3000, cold_df=2500)
    got2 = t2.search_many([qs[:1]], k=K)[0]
    _assert_identical(got2, t2.search_many_host([qs[:1]], k=K)[0],
                      "over-ladder fallback vs host")
    assert t2.stats["sparse_fallbacks"] > 0


def test_sparse_certificate_fallback():
    """force_cert_fail (the bool-path certificate test hook) discards the
    device collection on specs whose cold SHOULD side went through the
    sparse tier; the exact fallback still agrees bit-for-bit."""
    t = _turbo(_pcorpus(2200, 40, 9), 2200, cold_df=600)
    specs = [{"must": [("t0", 1.0)], "should": [("t30", 1.0)]},
             {"must": [("t2", 1.0)], "should": [("t25", 1.0),
                                                ("t33", 0.5)]}]
    want = t.search_bool_host(specs, k=K)
    fb0 = t.stats["fallbacks"]
    try:
        t.force_cert_fail = True
        got = t.search_bool(specs, k=K)
    finally:
        t.force_cert_fail = False
    _assert_identical(got, want, "cert-fail vs host")
    assert t.stats["fallbacks"] > fb0
    assert t.stats["sparse_queries"] > 0


@pytest.mark.faults
def test_sparse_fault_contained_per_partition():
    """An injected sparse_gather fault on one partition host-scores that
    partition's cold side only — results stay bit-identical, the fallback
    is counted, and a clean retry serves the device route again."""
    eng = _fused([(700, _pcorpus(700, 40, 12)),
                  (900, _pcorpus(900, 32, 13))], cold_df=250)
    qs = [[("t20", 1.0), ("t25", 1.0)], [("t1", 1.0), ("t28", 0.5)]]
    want = eng._merge3([t.search_many_host([qs], k=K)[0]
                        for t in eng.turbos], len(qs), K)
    fb0 = eng.turbos[1].stats["sparse_fallbacks"]
    with faults.inject("sparse_gather#1:raise@1"):
        got = eng.search_many([qs], k=K)[0]
    for g, w, name in zip(got, want, ("scores", "parts", "ords")):
        assert np.array_equal(np.asarray(g), np.asarray(w)), name
    assert eng.turbos[1].stats["sparse_fallbacks"] > fb0, \
        "faulted partition never fell back"
    clean = eng.search_many([qs], k=K)[0]
    for g, w, name in zip(clean, want, ("scores", "parts", "ords")):
        assert np.array_equal(np.asarray(g), np.asarray(w)), name


@pytest.mark.faults
def test_sparse_scrub_bitflip_repair():
    """PR-15 integrity plane over the slice pool: an injected hbm_region
    flip on sparse_pool is detected by the scrubber, repaired from the
    host mirror, and the repaired engine answers bit-identically."""
    fp = _pcorpus(1400, 36, 14)
    qs = [[("t20", 1.0), ("t25", 1.0)], [("t1", 1.0), ("t28", 0.5)]]
    control = _turbo(fp, 1400, cold_df=300)
    want = control.search_many([qs], k=K)[0]
    _assert_identical(want, control.search_many_host([qs], k=K)[0],
                      "control")

    integrity.reset_scrub_for_tests()      # only the engine below scrubs
    t = _turbo(fp, 1400, cold_df=300)
    t.search_many([qs], k=K)               # builds slices, registers region
    assert t._sp_pool is not None

    def cycle():
        return [integrity.scrub_once()
                for _ in range(integrity.scrub_registry_size())]

    cycle()                                # baseline pass: all clean
    m0 = integrity.integrity_stats()["scrub_mismatches"]
    with faults.inject("hbm_region#sparse_pool:raise@1x1"):
        results = cycle()
    hit = [r for r in results if r and r["result"] == "mismatch"]
    assert len(hit) == 1 and hit[0]["region"].endswith(".sparse_pool")
    st = integrity.integrity_stats()
    assert st["scrub_mismatches"] == m0 + 1
    assert st["scrub_repairs"] >= 1
    _assert_identical(t.search_many([qs], k=K)[0], want,
                      "repaired sparse engine vs control")
    cycle()                                # repair re-baselined the region
    assert integrity.integrity_stats()["scrub_mismatches"] == m0 + 1


def test_sparse_prewarm_and_hot_terms():
    """The relocation warm-handoff surface: sparse_hot_terms reports the
    resident slice set; prewarm_sparse rebuilds it on a cold engine so
    the first query after a move needs no slice build."""
    fp = _pcorpus(2000, 40, 15)
    src = _turbo(fp, 2000, cold_df=400)
    qs = [[("t20", 1.0), ("t30", 1.0)], [("t25", 1.0)]]
    src.search_many([qs], k=K)
    hot = src.sparse_hot_terms()
    assert hot, "no slices resident after cold-term traffic"

    dst = _turbo(fp, 2000, cold_df=400)
    n = dst.prewarm_sparse(hot)
    assert n == len(hot)
    assert dst.sparse_hot_terms() == hot
    s0 = dst.stats["sparse_slices"]
    got = dst.search_many([qs], k=K)[0]
    _assert_identical(got, src.search_many_host([qs], k=K)[0],
                      "prewarmed vs host")
    assert dst.stats["sparse_slices"] == s0, "prewarmed slices rebuilt"
    # colized terms never slice; unknown terms are ignored
    assert dst.prewarm_sparse(["t0", "absent"]) == 0


def test_sparse_knob_defaults():
    assert bool(knob("ES_TPU_SPARSE")) is True
    assert _sparse_widths() == (1024, 4096, 16384)

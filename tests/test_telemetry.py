"""Device telemetry plane (PR 12): HBM residency ledger, compile-cache
introspection, Prometheus exposition, and the nodes-stats fan-out.

The load-bearing contracts:
  * telemetry is pure observation — results are bit-identical with the
    sampler armed vs disabled (ES_TPU_METRICS_SAMPLE_S=0);
  * `tpu_hbm.occupancy_bytes` mirrors the engines' own `hbm_bytes()`
    arithmetic EXACTLY, through eviction churn and rebuilds;
  * /_tpu/metrics is one valid cluster-wide Prometheus document covering
    every declared metric, with dead peers degrading to node_up 0 rows.
"""

import re
import time

import numpy as np
import pytest

from elasticsearch_tpu.cluster_node import form_local_cluster
from elasticsearch_tpu.common import hbm_ledger, metrics
from elasticsearch_tpu.index.segment import build_field_postings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.parallel.spmd import build_stacked_bm25
from elasticsearch_tpu.parallel.turbo import TurboBM25
from elasticsearch_tpu.rest import RestController, register_handlers


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    metrics.reset_for_tests()
    hbm_ledger.reset_for_tests()
    yield
    metrics.reset_for_tests()
    hbm_ledger.reset_for_tests()


class _Seg:
    def __init__(self, n_docs, fp):
        self.n_docs = n_docs
        self.postings = {"body": fp}
        self.vectors = {}


def _corpus(n_docs=2000, vocab=60, seed=5):
    rng = np.random.default_rng(seed)
    probs = 1.0 / np.arange(1, vocab + 1) ** 1.1
    probs /= probs.sum()
    lens = rng.integers(4, 20, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum()), p=probs).astype(np.int64)
    names = [f"t{i}" for i in range(vocab)]
    tok_docs = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    fp = build_field_postings("body", lens, tok_docs, tokens, names)
    return fp, rng


def _turbo(seed=5, **kw):
    fp, rng = _corpus(seed=seed)
    stacked = build_stacked_bm25([_Seg(2000, fp)], "body", serve_only=True)
    kw.setdefault("hbm_budget_bytes", 64 << 20)
    kw.setdefault("cold_df", 10)
    return TurboBM25(stacked, **kw), rng


# ------------------------------------------------------------------ differential


def test_telemetry_armed_is_bit_identical_to_disabled(monkeypatch):
    """The sampler thread plus every ledger hook must not perturb a single
    bit of the scoring path."""

    def run(sample_s):
        monkeypatch.setenv("ES_TPU_METRICS_SAMPLE_S", sample_s)
        metrics.reset_for_tests()
        hbm_ledger.reset_for_tests()
        armed = metrics.maybe_start_sampler()
        turbo, rng = _turbo(seed=11)
        queries = [[f"t{a}", f"t{b}"] for a, b in
                   rng.integers(0, 60, size=(16, 2))]
        scores, ords = turbo.search(queries, k=10)
        return armed, np.asarray(scores).tobytes(), np.asarray(ords).tobytes()

    armed, s1, o1 = run("0.01")
    assert armed is True
    time.sleep(0.05)           # let the sampler take at least one snapshot
    assert len(metrics.metrics_history()) >= 1
    disarmed, s2, o2 = run("0")
    assert disarmed is False
    assert s1 == s2 and o1 == o2


# ------------------------------------------------------------ ledger exactness


def test_ledger_matches_hbm_bytes_exactly_under_churn():
    turbo, _ = _turbo(seed=7, hbm_budget_bytes=1, cold_df=5)
    assert turbo.Hp == 32
    assert turbo._hbm.total_bytes() == turbo.hbm_bytes()
    assert hbm_ledger.hbm_stats()["occupancy_bytes"] == turbo.hbm_bytes()
    # fill past capacity in two waves so the second forcibly evicts
    turbo.search([[f"t{i}"] for i in range(30)], k=5)
    turbo.search([[f"t{i}"] for i in range(30, 60)], k=5)
    st = hbm_ledger.hbm_stats()
    assert st["evictions"] > 0
    assert st["churn_bytes"] > 0
    assert turbo._hbm.total_bytes() == turbo.hbm_bytes()
    assert st["occupancy_bytes"] == turbo.hbm_bytes()
    assert st["high_watermark_bytes"] >= st["occupancy_bytes"]
    assert st["budget_bytes"] >= 0
    (entry,) = st["engines"].values()
    assert entry["kind"] == "turbo"
    assert entry["occupancy_bytes"] == turbo.hbm_bytes()


def test_ledger_drops_engine_on_gc():
    turbo, _ = _turbo(seed=9)
    occ = hbm_ledger.hbm_stats()["occupancy_bytes"]
    assert occ == turbo.hbm_bytes() > 0
    del turbo
    import gc
    gc.collect()
    st = hbm_ledger.hbm_stats()
    assert st["occupancy_bytes"] == 0
    assert st["engines"] == {}


# ------------------------------------------------------ compile introspection


def test_compile_cache_introspection_hits_misses_priming():
    turbo, rng = _turbo(seed=1)
    queries = [[f"t{a}", f"t{b}"] for a, b in
               rng.integers(0, 60, size=(12, 2))]
    turbo.search(queries, k=10)
    cs1 = hbm_ledger.compile_stats()
    assert cs1["misses"] >= 1
    assert cs1["events"], "first traces must record compile events"
    ev = cs1["events"][0]
    assert ev["engine"] == "turbo" and ev["wall_ms"] >= 0.0
    # the same shapes again: pure cache hits, no new traces
    turbo.search(queries, k=10)
    cs2 = hbm_ledger.compile_stats()
    assert cs2["misses"] == cs1["misses"]
    assert cs2["hits"] > cs1["hits"]
    assert 0.0 < cs2["warmup_coverage_ratio"] <= 1.0
    # bucket priming surfaces in primed_shapes and flips retrace accounting
    turbo.extend_qc_sizes((128,))
    cs3 = hbm_ledger.compile_stats()
    assert "turbo:128" in cs3["primed_shapes"]
    assert cs3["retraces"] == cs2["retraces"]


def test_turbo_eligible_records_routing_reason():
    from elasticsearch_tpu.search.serving import turbo_eligible

    fp, _ = _corpus(seed=3)
    eligible = turbo_eligible([_Seg(2000, fp)], "body", None)
    last = hbm_ledger.last_routing()
    assert last is not None
    assert last["index"] == "body"
    assert last["eligible"] is eligible
    # on the CPU test mesh the backend gate decides (unless forced)
    assert last["reason"] in ("backend_not_tpu", "forced_turbo",
                              "fits_hbm_budget", "exceeds_hbm_budget")
    assert hbm_ledger.last_routing_reason() == last["reason"]


# ------------------------------------------------------- Prometheus exposition

_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?\d+(\.\d+)?([eE][+-]?\d+)?$')


def test_prometheus_exposition_golden_format():
    metrics.counter_add("sched_flushes")
    metrics.gauge_set("sched_inflight", 3)
    metrics.observe("device", 1.5)
    metrics.observe("device", 250.0)
    text = metrics.render_prometheus(
        {"a": metrics.scrape_payload()}, [{"node_id": "b"}])
    assert text.endswith("\n")
    lines = text.splitlines()
    for ln in lines:
        if not ln.startswith("#"):
            assert _PROM_LINE.match(ln), f"invalid exposition line: {ln!r}"
    assert 'es_tpu_node_up{node="a"} 1' in lines
    assert 'es_tpu_node_up{node="b"} 0' in lines
    assert "# TYPE es_tpu_sched_flushes_total counter" in lines
    assert 'es_tpu_sched_flushes_total{node="a"} 1' in lines
    assert "# TYPE es_tpu_sched_inflight gauge" in lines
    assert 'es_tpu_sched_inflight{node="a"} 3' in lines
    # histogram: cumulative le buckets, +Inf == _count, sum of samples
    assert "# TYPE es_tpu_device histogram" in lines
    buckets = [ln for ln in lines
               if ln.startswith('es_tpu_device_bucket{node="a"')]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert buckets[-1].startswith('es_tpu_device_bucket{node="a",le="+Inf"}')
    assert counts[-1] == 2
    assert 'es_tpu_device_count{node="a"} 2' in lines
    assert 'es_tpu_device_sum{node="a"} 251.5' in lines
    # EVERY declared metric renders — the acceptance bar for the scrape
    for name in metrics.DECLARED_COUNTERS:
        assert f"# TYPE {metrics._prom_name(name)}_total counter" in lines
    for name in metrics.DECLARED_GAUGES:
        assert f"# TYPE {metrics._prom_name(name)} gauge" in lines
    for name in metrics.DECLARED:
        assert f"# TYPE {metrics._prom_name(name)} histogram" in lines


# ------------------------------------------------------------------- fan-out


def test_nodes_stats_fanout_degrades_over_dead_peer():
    nodes, store, channels = form_local_cluster(["a", "b"])
    a, b = nodes
    per_node, failures = a.telemetry_plane.nodes_stats()
    assert set(per_node) == {"a", "b"} and failures == []
    for sec in per_node.values():
        assert "tpu_hbm" in sec and "tpu_compile" in sec
        assert "occupancy_bytes" in sec["tpu_hbm"]
    channels.kill("b")
    per_node, failures = a.telemetry_plane.nodes_stats()
    assert set(per_node) == {"a"}
    assert [f["node_id"] for f in failures] == ["b"]
    assert failures[0]["type"] == "failed_node_exception"
    assert failures[0]["caused_by"]["type"] == "node_not_connected_exception"
    text, pfail = a.telemetry_plane.prometheus()
    assert 'es_tpu_node_up{node="a"} 1' in text
    assert 'es_tpu_node_up{node="b"} 0' in text
    assert [f["node_id"] for f in pfail] == ["b"]
    channels.revive("b")
    per_node, failures = a.telemetry_plane.nodes_stats()
    assert set(per_node) == {"a", "b"} and failures == []


# ---------------------------------------------------------------- REST surface


def test_rest_metrics_endpoints_and_nodes_stats_sections():
    node = Node()
    rc = RestController()
    register_handlers(node, rc)
    try:
        r = rc.dispatch("GET", "/_tpu/metrics", {}, None)
        assert r.status == 200
        assert r.content_type.startswith("text/plain")
        assert "# TYPE es_tpu_node_up gauge" in r.body
        assert "# TYPE es_tpu_sched_inflight gauge" in r.body
        h = rc.dispatch("GET", "/_tpu/metrics/history", {}, None)
        assert h.status == 200
        assert h.body["sampler_running"] is False   # knob defaults to 0
        assert isinstance(h.body["samples"], list)
        st = rc.dispatch("GET", "/_nodes/stats", {}, None)
        assert st.status == 200
        assert st.body["_nodes"]["failed"] == 0
        sec = st.body["nodes"][node.node_id]
        assert sec["tpu_hbm"]["occupancy_bytes"] >= 0
        assert "warmup_coverage_ratio" in sec["tpu_compile"]
    finally:
        node.close()


def test_sample_now_includes_scheduler_provider():
    s = metrics.sample_now()
    assert "ts" in s and "counters" in s and "gauges" in s
    assert "tpu_scheduler" in s
    assert set(metrics.metrics_history()[-1]) == set(s)

"""End-to-end engine + query DSL + query/fetch phase tests."""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import InternalEngine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import execute_search

MAPPING = {
    "properties": {
        "title": {"type": "text"},
        "body": {"type": "text"},
        "tags": {"type": "keyword"},
        "views": {"type": "long"},
        "price": {"type": "double"},
        "published": {"type": "date"},
        "active": {"type": "boolean"},
        "vec": {"type": "dense_vector", "dims": 4},
    }
}

DOCS = {
    "1": {"title": "quick brown fox", "body": "the quick brown fox jumps over the lazy dog",
          "tags": ["animal", "classic"], "views": 100, "price": 9.99,
          "published": "2020-01-01", "active": True, "vec": [1.0, 0.0, 0.0, 0.0]},
    "2": {"title": "lazy dog", "body": "the dog sleeps all day long, what a lazy dog",
          "tags": ["animal"], "views": 50, "price": 19.99,
          "published": "2021-06-15", "active": False, "vec": [0.0, 1.0, 0.0, 0.0]},
    "3": {"title": "jax on tpu", "body": "jax compiles numerical programs for tpus",
          "tags": ["tech"], "views": 500, "price": 0.0,
          "published": "2022-03-10", "active": True, "vec": [0.0, 0.0, 1.0, 0.0]},
    "4": {"title": "search engines", "body": "search engines rank documents with bm25 scoring",
          "tags": ["tech", "search"], "views": 250, "price": 49.50,
          "published": "2023-11-20", "active": True, "vec": [0.9, 0.1, 0.0, 0.0]},
}


@pytest.fixture(scope="module")
def engine():
    e = InternalEngine(MapperService(dict(MAPPING)))
    for doc_id, src in DOCS.items():
        e.index(doc_id, src)
        if doc_id == "2":
            e.refresh()  # force multi-segment coverage
    e.refresh()
    return e


def search(engine, request):
    return execute_search(engine.acquire_searcher(), engine.mapper, request, "test")


def ids(resp):
    return [h["_id"] for h in resp["hits"]["hits"]]


def test_match_all(engine):
    r = search(engine, {"query": {"match_all": {}}})
    assert r["hits"]["total"]["value"] == 4
    assert len(r["hits"]["hits"]) == 4
    assert all(h["_score"] == 1.0 for h in r["hits"]["hits"])


def test_match_ranking_and_idf(engine):
    r = search(engine, {"query": {"match": {"body": "lazy dog"}}})
    assert ids(r)[0] == "2"  # two "lazy"+"dog" occurrences ranks first
    assert r["hits"]["total"]["value"] == 2
    assert r["hits"]["max_score"] == r["hits"]["hits"][0]["_score"] > 0


def test_match_operator_and(engine):
    r_or = search(engine, {"query": {"match": {"body": "quick tpus"}}})
    assert r_or["hits"]["total"]["value"] == 2
    r_and = search(engine, {"query": {"match": {"body": {"query": "quick fox", "operator": "and"}}}})
    assert ids(r_and) == ["1"]


def test_term_keyword_and_numeric(engine):
    r = search(engine, {"query": {"term": {"tags": "tech"}}})
    assert sorted(ids(r)) == ["3", "4"]
    r = search(engine, {"query": {"term": {"views": 500}}})
    assert ids(r) == ["3"]
    r = search(engine, {"query": {"term": {"active": "true"}}})
    assert sorted(ids(r)) == ["1", "3", "4"]


def test_terms_query(engine):
    r = search(engine, {"query": {"terms": {"tags": ["classic", "search"]}}})
    assert sorted(ids(r)) == ["1", "4"]


def test_range_numeric_and_date(engine):
    r = search(engine, {"query": {"range": {"views": {"gte": 100, "lt": 500}}}})
    assert sorted(ids(r)) == ["1", "4"]
    r = search(engine, {"query": {"range": {"published": {"gte": "2021-01-01", "lte": "2022-12-31"}}}})
    assert sorted(ids(r)) == ["2", "3"]
    r = search(engine, {"query": {"range": {"price": {"gt": 9.99}}}})
    assert sorted(ids(r)) == ["2", "4"]


def test_bool_query_combinations(engine):
    r = search(engine, {"query": {"bool": {
        "must": [{"match": {"body": "dog"}}],
        "filter": [{"term": {"tags": "animal"}}],
        "must_not": [{"term": {"active": True}}],
    }}})
    assert ids(r) == ["2"]
    r = search(engine, {"query": {"bool": {
        "should": [{"term": {"tags": "classic"}}, {"term": {"tags": "search"}}],
    }}})
    assert sorted(ids(r)) == ["1", "4"]
    r = search(engine, {"query": {"bool": {
        "should": [{"term": {"tags": "animal"}}, {"term": {"active": True}},
                   {"range": {"views": {"gte": 200}}}],
        "minimum_should_match": 2,
    }}})
    assert sorted(ids(r)) == ["1", "3", "4"]


def test_bool_filter_only_scores_zero(engine):
    r = search(engine, {"query": {"bool": {"filter": [{"term": {"tags": "tech"}}]}}})
    assert all(h["_score"] == 0.0 for h in r["hits"]["hits"])


def test_match_phrase(engine):
    r = search(engine, {"query": {"match_phrase": {"body": "quick brown fox"}}})
    assert ids(r) == ["1"]
    r = search(engine, {"query": {"match_phrase": {"body": "fox brown"}}})
    assert ids(r) == []
    r = search(engine, {"query": {"match_phrase": {"body": {"query": "quick fox", "slop": 1}}}})
    assert ids(r) == ["1"]


def test_exists_prefix_wildcard_ids(engine):
    r = search(engine, {"query": {"exists": {"field": "price"}}})
    assert r["hits"]["total"]["value"] == 4
    r = search(engine, {"query": {"prefix": {"tags": "cla"}}})
    assert ids(r) == ["1"]
    r = search(engine, {"query": {"wildcard": {"tags": "se*ch"}}})
    assert ids(r) == ["4"]
    r = search(engine, {"query": {"ids": {"values": ["2", "3"]}}})
    assert sorted(ids(r)) == ["2", "3"]


def test_constant_score_and_boost(engine):
    r = search(engine, {"query": {"constant_score": {"filter": {"term": {"tags": "tech"}}, "boost": 2.5}}})
    assert all(h["_score"] == 2.5 for h in r["hits"]["hits"])


def test_multi_match(engine):
    r = search(engine, {"query": {"multi_match": {"query": "fox engines", "fields": ["title", "body"]}}})
    assert set(ids(r)) == {"1", "4"}


def test_function_score(engine):
    r = search(engine, {"query": {"function_score": {
        "query": {"term": {"tags": "tech"}},
        "functions": [{"field_value_factor": {"field": "views", "factor": 1.0, "modifier": "none"}}],
    }}})
    assert ids(r)[0] == "3"  # 500 views beats 250


def test_pagination_and_size(engine):
    r = search(engine, {"query": {"match_all": {}}, "size": 2, "sort": [{"views": {"order": "desc"}}]})
    assert ids(r) == ["3", "4"]
    r2 = search(engine, {"query": {"match_all": {}}, "size": 2, "from": 2,
                         "sort": [{"views": {"order": "desc"}}]})
    assert ids(r2) == ["1", "2"]


def test_sort_by_field_asc_desc_and_sort_values(engine):
    r = search(engine, {"query": {"match_all": {}}, "sort": [{"price": "asc"}]})
    assert ids(r) == ["3", "1", "2", "4"]
    assert r["hits"]["hits"][0]["sort"] == [0.0]
    r = search(engine, {"query": {"match_all": {}}, "sort": [{"published": {"order": "desc"}}]})
    assert ids(r) == ["4", "3", "2", "1"]


def test_sort_by_keyword(engine):
    r = search(engine, {"query": {"term": {"tags": "tech"}}, "sort": [{"tags": "asc"}]})
    assert ids(r) == ["4", "3"]  # "search" < "tech"... doc4 first keyword is "tech"? check below


def test_knn_section(engine):
    r = search(engine, {"knn": {"field": "vec", "query_vector": [1.0, 0.05, 0.0, 0.0], "k": 2}})
    assert ids(r)[0] in ("1", "4")
    assert len(ids(r)) == 2


def test_knn_with_filter(engine):
    r = search(engine, {"knn": {"field": "vec", "query_vector": [1.0, 0.0, 0.0, 0.0], "k": 4,
                                "filter": {"term": {"tags": "tech"}}}, "size": 4})
    assert set(ids(r)) <= {"3", "4"}


def test_hybrid_query_plus_knn(engine):
    r = search(engine, {"query": {"match": {"body": "bm25 scoring"}},
                        "knn": {"field": "vec", "query_vector": [0.9, 0.1, 0.0, 0.0], "k": 2}})
    assert ids(r)[0] == "4"  # matches both text and vector


def test_source_filtering(engine):
    r = search(engine, {"query": {"ids": {"values": ["1"]}}, "_source": ["title", "views"]})
    src = r["hits"]["hits"][0]["_source"]
    assert set(src) == {"title", "views"}
    r = search(engine, {"query": {"ids": {"values": ["1"]}}, "_source": False})
    assert "_source" not in r["hits"]["hits"][0]
    r = search(engine, {"query": {"ids": {"values": ["1"]}},
                        "_source": {"excludes": ["vec", "body"]}})
    src = r["hits"]["hits"][0]["_source"]
    assert "vec" not in src and "body" not in src and "title" in src


def test_fields_api(engine):
    r = search(engine, {"query": {"ids": {"values": ["4"]}}, "fields": ["views", "tags"]})
    f = r["hits"]["hits"][0]["fields"]
    assert f["views"] == [250.0]
    assert f["tags"] == ["search", "tech"]  # doc-values (sorted set) order


def test_track_total_hits(engine):
    r = search(engine, {"query": {"match_all": {}}, "track_total_hits": 2, "size": 1})
    assert r["hits"]["total"]["relation"] == "gte"
    r = search(engine, {"query": {"match_all": {}}, "track_total_hits": True})
    assert r["hits"]["total"] == {"value": 4, "relation": "eq"}


def test_deleted_docs_invisible(engine):
    # fresh engine to avoid mutating the module fixture
    e = InternalEngine(MapperService(dict(MAPPING)))
    for doc_id, src in DOCS.items():
        e.index(doc_id, src)
    e.refresh()
    e.delete("1")
    r = execute_search(e.acquire_searcher(), e.mapper, {"query": {"match": {"body": "fox"}}}, "t")
    assert r["hits"]["total"]["value"] == 0


def test_scores_consistent_across_segmentation():
    """BM25 must be identical whether docs are in 1 segment or 3 (shard stats)."""
    def build(refresh_points):
        e = InternalEngine(MapperService(dict(MAPPING)))
        for i, (doc_id, src) in enumerate(DOCS.items()):
            e.index(doc_id, src)
            if i in refresh_points:
                e.refresh()
        e.refresh()
        return e

    req = {"query": {"match": {"body": "the lazy dog"}}}
    r1 = execute_search(build(set()).acquire_searcher(), MapperService(dict(MAPPING)), req, "t")
    r2 = execute_search(build({0, 2}).acquire_searcher(), MapperService(dict(MAPPING)), req, "t")
    s1 = {h["_id"]: h["_score"] for h in r1["hits"]["hits"]}
    s2 = {h["_id"]: h["_score"] for h in r2["hits"]["hits"]}
    assert s1.keys() == s2.keys()
    for k in s1:
        assert s1[k] == pytest.approx(s2[k], rel=1e-5)

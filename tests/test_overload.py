"""Overload control plane tests (PR 13).

Covers the three consumers of `common/overload.py` plus the satellite
fixes that rode along:

* controller levels, deterministic fault injection, hysteresis (no
  GREEN<->RED flapping under a square-wave load — fake clock, no sleeps);
* `RetryBudget` token-bucket semantics (spend / refill / cap / disable);
* pool rejection satellites: shutdown-path rejections are counted and
  every `EsRejectedExecutionError` carries a `retry_after_s` hint;
* breaker satellites: the trip message reports bytes-wanted vs bytes
  already used, and a parent-level trip increments the PARENT's
  trip_count (visible in the hierarchy service's stats());
* REST seeded overload-storm differential: admitted queries stay
  bit-identical to an unloaded run, shed requests are clean 429s with
  Retry-After, every shed is counted;
* retry-budget fail-fast differential on the distributed harness: a
  seeded rpc_query storm is bounded by the budget (the organic error
  surfaces), while the ratio=0 run retries without bound;
* pressure propagation: data nodes piggyback their level on shard RPC
  responses and `_rank_copies` demotes overloaded replicas;
* chaos lane: overload shedding interleaved with a primary crash +
  restart loses no acked write (linearizability check).
"""

import json
import threading
import time

import pytest

from elasticsearch_tpu.common import faults, metrics, overload
from elasticsearch_tpu.common.breaker import (
    CircuitBreaker, CircuitBreakingError, HierarchyCircuitBreakerService,
)
from elasticsearch_tpu.common.durability import reset_for_tests
from elasticsearch_tpu.common.faults import inject
from elasticsearch_tpu.common.overload import OverloadController, RetryBudget
from elasticsearch_tpu.threadpool.pool import (
    EsRejectedExecutionError, FixedExecutor,
)

MAPPINGS = {"properties": {"n": {"type": "integer"},
                           "body": {"type": "text"}}}


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    overload.reset_default_for_tests()
    yield
    faults.clear()
    overload.reset_default_for_tests()


def make_controller(**kw):
    """Controller on a fake clock so hysteresis tests need no sleeps."""
    t = {"now": 0.0}
    ctl = OverloadController("test", clock=lambda: t["now"], **kw)
    return ctl, t


# ------------------------------------------------------------ level folding


def test_green_by_default_and_signals_normalized():
    ctl, _ = make_controller()
    assert ctl.evaluate() == "green"
    st = ctl.stats()
    assert st["level"] == "green"
    # the hbm signal reads the process-global ledger, which other suites
    # may have touched — advisory weighting keeps it far from YELLOW
    assert st["score"] < 0.5
    # unwired signals read 0, never None/missing
    for k in ("pool_queue", "queue_wait", "scheduler", "breaker",
              "indexing"):
        assert st["signals"][k] == 0.0
    assert 0.0 <= st["signals"]["hbm"] <= 1.0


def test_injected_levels_map_hang_yellow_raise_red(monkeypatch):
    monkeypatch.setenv("ES_TPU_OVERLOAD_HYSTERESIS_MS", "0")
    ctl, _ = make_controller()
    with inject("overload_pressure:hang@1x1"):
        assert ctl.evaluate() == "yellow"
    assert ctl.evaluate() == "green"   # clause consumed, hysteresis off
    with inject("overload_pressure:raise@1x1"):
        assert ctl.evaluate() == "red"
    with inject("overload_pressure:oom@1x1"):
        assert ctl.evaluate() == "red"
    assert ctl.evaluate() == "green"
    assert "green->red" in ctl.stats()["transitions"]


def test_stats_reports_cached_level_without_consuming_injection():
    ctl, _ = make_controller()
    with inject("overload_pressure:raise@1x1"):
        # stats() must not consume the single injected fire
        for _ in range(5):
            assert ctl.stats()["level"] == "green"
        assert ctl.evaluate() == "red"


def test_hysteresis_square_wave_no_flapping(monkeypatch):
    """A 0.2s-period square wave against a 500ms hysteresis window must
    hold RED (upgrades immediate, downgrades deferred), then decay to
    GREEN only after the raw level stays below for the full window."""
    monkeypatch.setenv("ES_TPU_OVERLOAD_HYSTERESIS_MS", "500")
    ctl, t = make_controller()
    for _ in range(6):
        with inject("overload_pressure:raise@1x1"):
            assert ctl.evaluate() == "red"
        t["now"] += 0.1
        # raw green, but inside the hysteresis window: level holds
        assert ctl.evaluate() == "red"
        t["now"] += 0.1
    assert ctl.stats()["transitions"] == ["green->red"], \
        "square wave must not flap GREEN<->RED"
    # sustained green for > window: downgrade exactly once
    assert ctl.evaluate() == "red"
    t["now"] += 0.6
    assert ctl.evaluate() == "green"
    assert ctl.stats()["transitions"] == ["green->red", "red->green"]


# ------------------------------------------------------------- retry budget


def test_retry_budget_spend_refill_cap(monkeypatch):
    monkeypatch.setenv("ES_TPU_RETRY_BUDGET_CAP", "3")
    monkeypatch.setenv("ES_TPU_RETRY_BUDGET_RATIO", "0.5")
    b = RetryBudget()   # cap read at construction = initial fill
    assert [b.allow("s") for _ in range(3)] == [True, True, True]
    assert b.allow("s") is False
    assert b.allow("other") is False
    st = b.stats()
    assert st["consumed"] == 3
    assert st["exhausted"] == {"s": 1, "other": 1}
    assert st["exhausted_total"] == 2
    # one success refills ratio=0.5: still below a whole token
    b.note_success()
    assert b.allow("s") is False
    b.note_success()
    assert b.allow("s") is True      # 1.0 token accumulated
    # refills cap at ES_TPU_RETRY_BUDGET_CAP
    for _ in range(100):
        b.note_success()
    assert b.stats()["tokens"] == 3.0


def test_retry_budget_ratio_zero_disables(monkeypatch):
    monkeypatch.setenv("ES_TPU_RETRY_BUDGET_CAP", "1")
    monkeypatch.setenv("ES_TPU_RETRY_BUDGET_RATIO", "0")
    b = RetryBudget()
    assert all(b.allow("s") for _ in range(50))
    st = b.stats()
    assert st["consumed"] == 0 and st["exhausted_total"] == 0


# -------------------------------------------------- pool rejection satellites


def test_pool_shutdown_rejection_counted_with_retry_after():
    ex = FixedExecutor("probe", 1, 4)
    ex.shutdown()
    with pytest.raises(EsRejectedExecutionError) as ei:
        ex.submit(lambda: None)
    assert ei.value.metadata["retry_after_s"] >= 1
    assert ex.stats()["rejected"] == 1, \
        "shutdown-path rejection must bump the rejected counter"


def test_pool_queue_full_rejection_carries_retry_after():
    ex = FixedExecutor("probe", 1, 0)
    started, release = threading.Event(), threading.Event()

    def block():
        started.set()
        release.wait(5)

    ex.submit(block)
    assert started.wait(5)
    try:
        with pytest.raises(EsRejectedExecutionError) as ei:
            ex.submit(lambda: None)
        assert ei.value.metadata["retry_after_s"] >= 1
        assert ex.stats()["rejected"] == 1
    finally:
        release.set()
        ex.shutdown()


# --------------------------------------------------------- breaker satellites


def test_breaker_trip_message_wanted_vs_already_used():
    br = CircuitBreaker("request", limit_bytes=100)
    br.add_estimate_bytes_and_maybe_break(60, "chunk-a")
    with pytest.raises(CircuitBreakingError) as ei:
        br.add_estimate_bytes_and_maybe_break(60, "chunk-b")
    msg = str(ei.value)
    assert "wanted [60b] on top of [60b] already used" in msg
    assert "[120b]" in msg and "[100b]" in msg
    assert ei.value.metadata["bytes_wanted"] == 60
    assert ei.value.metadata["bytes_used"] == 60
    assert ei.value.metadata["bytes_limit"] == 100
    # failed reservation rolled back, trip recorded
    assert br.used_bytes == 60
    assert br.trip_count == 1


def test_parent_trip_increments_parent_count_and_rolls_back_child():
    parent = CircuitBreaker("parent", 100)
    child = CircuitBreaker("request", 1000, parent=parent)
    with pytest.raises(CircuitBreakingError):
        child.add_estimate_bytes_and_maybe_break(150, "big")
    assert parent.trip_count == 1
    assert child.trip_count == 0
    assert child.used_bytes == 0 and parent.used_bytes == 0


def test_hierarchy_service_stats_show_parent_trip():
    svc = HierarchyCircuitBreakerService(total_limit_bytes=1000)
    # fill the parent via untracked child reservations, then let a small
    # tracked add trip the PARENT (each child stays under its own limit)
    svc.get_breaker("request").add_without_breaking(950)
    with pytest.raises(CircuitBreakingError):
        svc.get_breaker("fielddata").add_estimate_bytes_and_maybe_break(
            60, "agg")
    st = svc.stats()
    assert st["parent"]["tripped"] == 1
    assert st["fielddata"]["tripped"] == 0


# ------------------------------------------------ REST admission differential


@pytest.fixture()
def api():
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest import RestController, register_handlers

    node = Node()
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, params=None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body)
        return rc.dispatch(method, path, params or {}, body)

    yield call, node
    node.close()


def test_rest_storm_differential_bit_identical(api, monkeypatch):
    """`overload_pressure:raise@3x2` sheds exactly the 3rd and 4th
    admission checks: those two searches come back as clean 429s with
    Retry-After; every admitted search is bit-identical to the unloaded
    baseline; every shed is counted."""
    monkeypatch.setenv("ES_TPU_OVERLOAD_HYSTERESIS_MS", "0")
    call, node = api
    for i in range(8):
        call("PUT", f"/idx/_doc/{i}",
             {"n": i, "body": f"word{i % 3} common text"})
    call("POST", "/idx/_refresh")
    body = {"query": {"match": {"body": "common"}}, "size": 5}
    baseline = [call("POST", "/idx/_search", body) for _ in range(6)]
    assert all(r.status == 200 for r in baseline)
    shed_before = metrics.counter_values()["overload_shed"]

    with inject("overload_pressure:raise@3x2"):
        results = [call("POST", "/idx/_search", body) for _ in range(6)]

    for i, r in enumerate(results):
        if i in (2, 3):
            assert r.status == 429
            assert r.body["error"]["type"] == "es_rejected_execution_exception"
            assert int(r.headers["Retry-After"]) >= 1
        else:
            assert r.status == 200
            assert r.body["hits"] == baseline[i].body["hits"], \
                "admitted queries must stay bit-identical under brownout"
    assert metrics.counter_values()["overload_shed"] - shed_before == 2

    # nodes-stats surface + Prometheus exposition
    st = node.overload.stats()
    assert st["shed"]["total"] == 2
    assert "green->red" in st["transitions"]
    r = call("GET", "/_nodes/stats")
    (node_stats,) = r.body["nodes"].values()
    assert node_stats["tpu_overload"]["shed"]["total"] == 2
    text = metrics.render_prometheus({"n": metrics.scrape_payload()}, [])
    assert "es_tpu_tpu_overload_level" in text
    assert "es_tpu_overload_shed_total" in text


def test_rest_yellow_sheds_bulk_keeps_interactive(api, monkeypatch):
    """Brownout ladder at YELLOW: bulk tier 429s (Retry-After set, nothing
    written), interactive searches and management endpoints stay admitted."""
    monkeypatch.setenv("ES_TPU_OVERLOAD_HYSTERESIS_MS", "0")
    call, node = api
    call("PUT", "/lib/_doc/1", {"n": 1, "body": "hello world"})
    call("POST", "/lib/_refresh")
    bulk = "\n".join([
        json.dumps({"index": {"_index": "lib", "_id": "9"}}),
        json.dumps({"n": 9, "body": "shed me"}),
    ]) + "\n"
    with inject("overload_pressure:hang@1xinf"):
        r = call("POST", "/_bulk", bulk)
        assert r.status == 429
        assert int(r.headers["Retry-After"]) >= 1
        assert r.body["error"]["type"] == "es_rejected_execution_exception"
        r = call("GET", "/lib/_search", {"query": {"match_all": {}}})
        assert r.status == 200, "interactive admitted at YELLOW"
        # management plane must stay reachable mid-brownout
        assert call("GET", "/_nodes/stats").status == 200
    # the shed bulk wrote nothing
    call("POST", "/lib/_refresh")
    r = call("GET", "/lib/_count")
    assert r.body["count"] == 1
    st = node.overload.stats()
    assert st["shed"]["bulk"] >= 1 and st["shed"]["interactive"] == 0


# --------------------------------------- retry-budget fail-fast differential


def test_retry_budget_bounds_failover_storm(monkeypatch):
    """Seeded rpc_query storm on a 1-shard/1-replica index: with a 3-token
    budget the failover loop performs exactly 3 retries then fails fast
    with the ORGANIC transport error; flipping the ratio knob to 0 on the
    same cluster restores unbounded (one-per-search) retries."""
    from elasticsearch_tpu.action.search_action import _COORD_COUNTERS
    from elasticsearch_tpu.cluster_node import form_local_cluster

    monkeypatch.setenv("ES_TPU_OVERLOAD_HYSTERESIS_MS", "0")
    monkeypatch.setenv("ES_TPU_RETRY_BUDGET_CAP", "3")
    monkeypatch.setenv("ES_TPU_RETRY_BUDGET_RATIO", "0.001")
    # keep the per-node transport circuit out of the way: this test wants
    # the retry BUDGET to be the binding constraint, not quarantine
    monkeypatch.setenv("ES_TPU_HEALTH_TRIP_N", "1000")
    nodes, store, channels = form_local_cluster(
        ["m0", "d0", "d1"], roles={"m0": ("master",)})
    master, a, b = nodes
    a.create_index("docs", {"settings": {"number_of_shards": 1,
                                         "number_of_replicas": 1},
                            "mappings": MAPPINGS})
    resp = a.bulk("docs", [{"op": "index", "id": f"x{i}",
                            "source": {"n": i, "body": "text"}}
                           for i in range(4)])
    assert not resp["errors"]
    a.refresh("docs")

    def storm(n):
        before = _COORD_COUNTERS["shard_retries"]
        with inject("rpc_query:raise@1xinf"):
            for _ in range(n):
                r = a.search("docs", {"query": {"match_all": {}}})
                assert r["_shards"]["failed"] == 1
                reason = r["_shards"]["failures"][0]["reason"]
                # fail-fast surfaces the organic transport error, never a
                # budget-shaped one
                assert reason["type"] == "node_not_connected_exception"
                assert "budget" not in json.dumps(r).lower()
        return _COORD_COUNTERS["shard_retries"] - before

    # budgeted: 3 tokens -> 3 failover retries total across 10 searches
    assert storm(10) == 3
    st = a.overload.stats()["retry_budget"]
    assert st["exhausted"]["shard_failover"] == 7
    assert st["tokens"] < 1

    # knob off: every search retries the second copy (10 retries for 10)
    monkeypatch.setenv("ES_TPU_RETRY_BUDGET_RATIO", "0")
    assert storm(10) == 10


# ------------------------------------------------------ pressure propagation


def test_pressure_piggyback_and_replica_demotion(monkeypatch):
    """Data nodes piggyback their level on shard RPC responses; the
    coordinator remembers it and `_rank_copies` demotes pressured copies
    (even the local one) until the signal ages out."""
    from elasticsearch_tpu.action.search_action import _COORD_COUNTERS
    from elasticsearch_tpu.cluster_node import form_local_cluster

    monkeypatch.setenv("ES_TPU_OVERLOAD_HYSTERESIS_MS", "0")
    nodes, store, channels = form_local_cluster(
        ["m0", "d0", "d1"], roles={"m0": ("master",)})
    master, a, b = nodes
    a.create_index("docs", {"settings": {"number_of_shards": 1,
                                         "number_of_replicas": 1},
                            "mappings": MAPPINGS})
    a.bulk("docs", [{"op": "index", "id": "1",
                     "source": {"n": 1, "body": "hello"}}])
    a.refresh("docs")

    # integration: a YELLOW data node piggybacks its level; interactive
    # searches stay admitted at YELLOW so the response is full-fidelity
    with inject("overload_pressure:hang@1xinf"):
        r = a.search("docs", {"query": {"match_all": {}}})
    assert r["_shards"]["failed"] == 0
    assert r["hits"]["total"]["value"] == 1
    sa = a.search_action
    assert any(lvl == "yellow" for lvl, _ in sa._node_pressure.values())

    # unit: a RED mark on the LOCAL node outranks locality
    copies = store.current().shard_copies("docs", 0)
    assert {c.node_id for c in copies} == {"d0", "d1"}
    sa._node_pressure.clear()
    sa._note_node_pressure("d0", "red")
    before = _COORD_COUNTERS["overload_reroutes"]
    assert sa._rank_copies(copies)[0] == "d1"
    assert _COORD_COUNTERS["overload_reroutes"] - before == 1

    # stale signals age out (TTL = max(1s, 2x hysteresis)): rank reverts
    sa._node_pressure["d0"] = ("red", time.monotonic() - 30.0)
    assert sa._rank_copies(copies)[0] == "d0"


# ----------------------------------------------------------------- chaos lane


def write_op(doc_id, value):
    return {"op": "index", "id": doc_id,
            "source": {"n": value, "body": f"v{value}"}}


def test_chaos_shedding_with_crash_restart_keeps_acked_writes(
        tmp_path, monkeypatch):
    """Overload shedding interleaved with a primary crash + restart: a
    shed bulk rejects the WHOLE request before any op applies (nothing
    acked), so the acked-write linearizability check still passes."""
    from elasticsearch_tpu.testing.chaos import (
        AckedWriteHistory, CrashRestartCluster,
    )

    monkeypatch.setenv("ES_TPU_OVERLOAD_HYSTERESIS_MS", "0")
    reset_for_tests()
    try:
        cluster = CrashRestartCluster(["m0", "d0", "d1", "d2"],
                                      str(tmp_path),
                                      roles={"m0": ("master",)})
        cluster.master().create_index(
            "docs", {"settings": {"number_of_shards": 1,
                                  "number_of_replicas": 1},
                     "mappings": MAPPINGS})
        history = AckedWriteHistory()
        docs = [f"doc{i}" for i in range(6)]

        def guarded_bulk(value):
            ops = [write_op(d, value) for d in docs]
            pending = [(op, history.invoke(op["id"], "write",
                                           op["source"]["n"]))
                       for op in ops]
            try:
                resp = cluster.master().bulk("docs", list(ops))
            except EsRejectedExecutionError:
                # shed at admission, before ANY op applied: nothing acked
                return set()
            acked = set()
            for (op, op_id), item in zip(pending, resp["items"]):
                if item is not None and "error" not in item:
                    history.respond(op["id"], op_id)
                    acked.add(op["id"])
            return acked

        def primary_node():
            for r in cluster.store.current().shard_copies("docs", 0):
                if r.primary and r.state == "STARTED":
                    return r.node_id
            return None

        assert guarded_bulk(1) == set(docs)          # green: all acked
        with inject("overload_pressure:hang@1xinf"):
            assert guarded_bulk(2) == set()          # yellow: whole bulk shed
        victim = primary_node()
        assert cluster.node(victim).overload.stats()["shed"]["bulk"] >= 1
        cluster.crash(victim)                        # promotion
        assert guarded_bulk(3) == set(docs)          # acked on new primary
        cluster.restart(victim)                      # peer recovery
        with inject("overload_pressure:hang@1xinf"):
            assert guarded_bulk(4) == set()          # shed again post-restart
        faults.clear()
        for d in docs:
            src = cluster.read_doc("docs", d)
            history.record_read(d, None if src is None else src["n"])
        assert history.check() == [], \
            "an acked write vanished across shed/crash/restart interleaving"
    finally:
        reset_for_tests()

"""Unified serving path: REST-level flat queries must produce IDENTICAL
results through the blockmax fast path and the dense executor.

VERDICT r2 weak #6 closure test: the same `_search` body runs through
IndexService.search (fast path engaged when eligible) and _search_dense
(the dense reference), and hits must match — ids, order (deterministic
doc-id tie-break on both sides), scores to f32 tolerance, totals exactly.
"""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.search.serving import extract_plan

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta", "theta",
         "iota", "kappa", "lam", "mu", "nu", "xi", "omicron", "pi"]
TAGS = ["red", "green", "blue", "yellow"]


@pytest.fixture(scope="module")
def svc():
    meta = IndexMetadata(
        index="t", uuid="u1", settings=Settings({}),
        mappings={"properties": {
            "body": {"type": "text"},
            "tag": {"type": "keyword"},
            "n": {"type": "integer"},
        }})
    svc = IndexService(meta)
    rng = np.random.default_rng(31)
    n_docs = 400
    for i in range(n_docs):
        words = rng.choice(WORDS, size=int(rng.integers(3, 20)))
        svc.index_doc(str(i), {
            "body": " ".join(words),
            "tag": str(rng.choice(TAGS)),
            "n": int(rng.integers(0, 100)),
        })
        if i == 150:
            svc.refresh()       # two segments in shard 0
    # deletions exercise live masks on both paths
    for i in range(0, 60, 7):
        svc.delete_doc(str(i))
    svc.refresh()
    yield svc
    svc.close()


BODIES = [
    {"query": {"match": {"body": "alpha beta"}}},
    {"query": {"match": {"body": "gamma"}}, "size": 25},
    {"query": {"term": {"body": {"value": "delta", "boost": 2.0}}}},
    {"query": {"match": {"body": {"query": "alpha beta gamma",
                                  "operator": "and"}}}},
    {"query": {"bool": {
        "must": [{"match": {"body": {"query": "alpha", "operator": "and"}}}],
        "filter": [{"term": {"tag": "red"}}]}}},
    {"query": {"bool": {
        "must": [{"term": {"body": "beta"}}],
        "should": [{"term": {"body": "gamma"}}, {"term": {"body": "pi"}}],
        "must_not": [{"term": {"tag": "blue"}}]}}},
    {"query": {"bool": {
        "filter": [{"terms": {"tag": ["red", "green"]}},
                   {"term": {"body": "epsilon"}}],
        "must": [{"match": {"body": {"query": "zeta", "operator": "and"}}}]}}},
    {"query": {"match_phrase": {"body": "alpha beta"}}},
    {"query": {"match_phrase": {"body": {"query": "alpha gamma", "slop": 2}}}},
    {"query": {"bool": {
        "must": [{"match_phrase": {"body": "beta gamma"}}],
        "filter": [{"term": {"tag": "green"}}]}}},
    {"query": {"match": {"body": "theta iota"}}, "from": 5, "size": 10},
    {"query": {"match": {"body": "kappa"}}, "track_total_hits": 20},
    {"query": {"match": {"body": "mu nu xi"}}, "track_total_hits": True},
    {"query": {"bool": {"should": [{"match": {"body": "omicron"}},
                                   {"term": {"body": "pi"}}]}}},
    # pure-should bool in FILTER context = required single-field OR-group
    {"query": {"bool": {
        "must": [{"match": {"body": {"query": "alpha", "operator": "and"}}}],
        "filter": [{"bool": {"should": [{"term": {"tag": "red"}},
                                        {"term": {"tag": "green"}}]}}]}}},
    # bool with required clauses + optional should inside filter ctx:
    # the should is a non-scoring no-op
    {"query": {"bool": {
        "filter": [{"bool": {"must": [{"term": {"body": "beta"}}],
                             "should": [{"term": {"tag": "red"}}]}}],
        "must": [{"term": {"body": "gamma"}}]}}},
]

INELIGIBLE = [
    {"query": {"match": {"body": "alpha"}}, "sort": [{"n": "asc"}]},
    {"query": {"match": {"body": "alpha"}},
     "aggs": {"m": {"max": {"field": "n"}}}},
    {"query": {"range": {"n": {"gte": 10}}}},
    {"query": {"bool": {"should": [{"match": {"body": "alpha"}}],
                        "minimum_should_match": 2}}},
    {"query": {"match_all": {}}},
    {"query": {"wildcard": {"body": {"value": "alp*"}}}},
    # pure-should bool under must is a required SCORED or-group: dense only
    {"query": {"bool": {
        "must": [{"bool": {"should": [{"term": {"body": "beta"}},
                                      {"term": {"body": "gamma"}}]}},
                 {"term": {"body": "alpha"}}]}}},
    # multi-alternative top should with a conjunctive alternative
    {"query": {"bool": {"should": [
        {"match": {"body": {"query": "alpha beta", "operator": "and"}}},
        {"term": {"body": "gamma"}}]}}},
]


def _hit_key(h):
    return h["_id"]


def assert_same_results(fast, dense, body):
    fh = fast["hits"]["hits"]
    dh = dense["hits"]["hits"]
    assert [h["_id"] for h in fh] == [h["_id"] for h in dh], body
    for a, b in zip(fh, dh):
        if a.get("_score") is not None and b.get("_score") is not None:
            assert abs(a["_score"] - b["_score"]) <= 2e-4 * abs(b["_score"]) + 2e-4, body
        assert a["_source"] == b["_source"]
    assert fast["hits"]["total"] == dense["hits"]["total"], body
    fm, dm = fast["hits"]["max_score"], dense["hits"]["max_score"]
    if fm is None or dm is None:
        assert fm == dm, body
    else:
        assert abs(fm - dm) <= 2e-4 * abs(dm) + 2e-4


@pytest.mark.parametrize("body", BODIES)
def test_fast_path_matches_dense(svc, body):
    plan = extract_plan(body, svc.mapper)
    assert plan is not None, f"expected eligible: {body}"
    fast = svc.serving.try_search(body, "query_then_fetch")
    assert fast is not None, f"fast path did not engage: {body}"
    dense = svc._search_dense(body)
    assert_same_results(fast, dense, body)


@pytest.mark.parametrize("body", INELIGIBLE)
def test_ineligible_bodies_fall_back(svc, body):
    assert extract_plan(body, svc.mapper) is None, body
    # and the public entry still answers via the dense path
    r = svc.search(body)
    assert "hits" in r


def test_msearch_batches_match_individual(svc):
    bodies = [
        {"query": {"match": {"body": "alpha"}}},
        {"query": {"match": {"body": "beta gamma"}}},
        {"query": {"range": {"n": {"gte": 50}}}},        # dense fallback
        {"query": {"bool": {"must": [{"term": {"body": "delta"}}],
                            "filter": [{"term": {"tag": "red"}}]}}},
    ]
    batch = svc.msearch(bodies)
    for body, br in zip(bodies, batch):
        single = svc._search_dense(body)
        assert_same_results(br, single, body)


def test_random_disjunctions_match(svc):
    rng = np.random.default_rng(7)
    for _ in range(25):
        n = int(rng.integers(1, 4))
        terms = rng.choice(WORDS, size=n, replace=False)
        body = {"query": {"match": {"body": " ".join(terms)}},
                "size": int(rng.integers(1, 30))}
        fast = svc.serving.try_search(body, "query_then_fetch")
        assert fast is not None
        assert_same_results(fast, svc._search_dense(body), body)


def test_track_total_hits_false_omits_total_on_both_paths(svc):
    body = {"query": {"match": {"body": "alpha"}}, "track_total_hits": False}
    fast = svc.serving.try_search(body, "query_then_fetch")
    dense = svc._search_dense(body)
    assert "total" not in fast["hits"] and "total" not in dense["hits"]
    assert [h["_id"] for h in fast["hits"]["hits"]] == \
        [h["_id"] for h in dense["hits"]["hits"]]


def test_msearch_isolates_per_body_errors(svc):
    from elasticsearch_tpu.common.errors import ElasticsearchTpuError

    bodies = [
        {"query": {"match": {"body": "alpha"}}},
        {"query": {"no_such_query": {}}},
        {"query": {"term": {"body": "beta"}}},
    ]
    out = svc.msearch(bodies)
    assert "hits" in out[0] and "hits" in out[2]
    assert isinstance(out[1], ElasticsearchTpuError)


def test_multi_shard_defaults_to_dense_but_dfs_serves():
    meta = IndexMetadata(
        index="m", uuid="u2",
        settings=Settings({"index.number_of_shards": 2}),
        mappings={"properties": {"body": {"type": "text"}}})
    svc = IndexService(meta)
    for i in range(100):
        svc.index_doc(str(i), {"body": f"alpha {WORDS[i % len(WORDS)]}"})
    svc.refresh()
    body = {"query": {"match": {"body": "alpha beta"}}}
    assert svc.serving.try_search(body, "query_then_fetch") is None
    fast = svc.serving.try_search(body, "dfs_query_then_fetch")
    assert fast is not None
    dense = svc._search_dense(body, "dfs_query_then_fetch")
    assert_same_results(fast, dense, body)
    svc.close()


# --------------------------------------------------------------------------
# TurboBM25 on the REST path (VERDICT r4 item 2)
# --------------------------------------------------------------------------


@pytest.fixture()
def turbo_svc(monkeypatch):
    """Index whose disjunctions route through TurboEngine: the backend gate
    is overridden (CPU runs the Pallas kernels in interpret mode) and
    cold_df lowered so real columns build. Two segments + deletions force
    the multi-partition merge path."""
    monkeypatch.setenv("ES_TPU_FORCE_TURBO", "1")
    monkeypatch.setenv("ES_TPU_TURBO_COLD_DF", "8")
    meta = IndexMetadata(
        index="turbo_t", uuid="u_turbo", settings=Settings({}),
        mappings={"properties": {"body": {"type": "text"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(99)
    for i in range(320):
        words = rng.choice(WORDS, size=int(rng.integers(3, 16)))
        svc.index_doc(str(i), {"body": " ".join(words)})
        if i == 140:
            svc.refresh()
    for i in range(0, 50, 9):
        svc.delete_doc(str(i))
    svc.refresh()
    yield svc
    svc.close()


def test_turbo_engine_selected_and_matches_dense(turbo_svc):
    svc = turbo_svc
    snap = svc.serving.snapshot()
    eng = snap.engine("body")
    assert eng.kind == "turbo"
    assert len(eng.turbos) == 2          # one per segment partition
    bodies = [
        {"query": {"match": {"body": "alpha beta"}}},
        {"query": {"match": {"body": "gamma"}}, "size": 20},
        {"query": {"term": {"body": {"value": "delta", "boost": 2.0}}}},
        {"query": {"match": {"body": "theta iota kappa"}}, "from": 3},
        {"query": {"match": {"body": "zzz_missing"}}},
    ]
    for body in bodies:
        fast = svc.serving.try_search(body, "query_then_fetch")
        assert fast is not None, body
        assert_same_results(fast, svc._search_dense(body), body)
    assert eng.stats["builds"] > 0       # columns actually engaged


def test_turbo_msearch_batch(turbo_svc):
    svc = turbo_svc
    bodies = [{"query": {"match": {"body": w}}} for w in
              ["alpha", "beta gamma", "pi omicron", "mu"]]
    batch = svc.msearch(bodies)
    for body, br in zip(bodies, batch):
        assert_same_results(br, svc._search_dense(body), body)

"""REST API conformance tests (in-process dispatch + one real-HTTP smoke)."""

import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest import HttpServer, RestController, register_handlers


@pytest.fixture()
def api():
    node = Node()
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, params=None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body)
        return rc.dispatch(method, path, params or {}, body)

    yield call, node
    node.close()


def test_root_info(api):
    call, _ = api
    r = call("GET", "/")
    assert r.status == 200
    assert r.body["tagline"] == "You Know, for Search"
    assert r.body["version"]["build_flavor"] == "tpu"


def test_index_crud(api):
    call, _ = api
    r = call("PUT", "/books", {"settings": {"number_of_shards": 2},
                               "mappings": {"properties": {"title": {"type": "text"}}}})
    assert r.status == 200 and r.body["acknowledged"]
    assert call("HEAD", "/books").status == 200
    assert call("HEAD", "/missing").status == 404
    r = call("GET", "/books")
    assert r.body["books"]["mappings"]["properties"]["title"]["type"] == "text"
    assert r.body["books"]["settings"]["index"]["number_of_shards"] == "2"
    r = call("PUT", "/books")
    assert r.status == 400  # already exists
    assert "resource_already_exists_exception" in json.dumps(r.body)
    assert call("DELETE", "/books").body["acknowledged"]
    assert call("HEAD", "/books").status == 404
    assert call("DELETE", "/missing").status == 404


def test_doc_crud_and_versioning(api):
    call, _ = api
    r = call("PUT", "/idx/_doc/1", {"title": "hello"})
    assert r.status == 201 and r.body["result"] == "created" and r.body["_version"] == 1
    r = call("PUT", "/idx/_doc/1", {"title": "hello again"})
    assert r.status == 200 and r.body["result"] == "updated" and r.body["_version"] == 2
    r = call("GET", "/idx/_doc/1")
    assert r.body["found"] and r.body["_source"]["title"] == "hello again"
    assert call("GET", "/idx/_source/1").body == {"title": "hello again"}
    assert call("HEAD", "/idx/_doc/1").status == 200
    r = call("PUT", "/idx/_create/1", {"title": "nope"})
    assert r.status == 409
    r = call("DELETE", "/idx/_doc/1")
    assert r.status == 200 and r.body["result"] == "deleted"
    assert call("GET", "/idx/_doc/1").status == 404
    # optimistic concurrency via url params
    r = call("PUT", "/idx/_doc/2", {"n": 1})
    seq = r.body["_seq_no"]
    r = call("PUT", "/idx/_doc/2", {"n": 2}, params={"if_seq_no": str(seq + 5), "if_primary_term": "1"})
    assert r.status == 409
    r = call("PUT", "/idx/_doc/2", {"n": 2}, params={"if_seq_no": str(seq), "if_primary_term": "1"})
    assert r.status == 200


def test_auto_id_and_update(api):
    call, _ = api
    r = call("POST", "/idx/_doc", {"x": 1})
    assert r.status == 201 and len(r.body["_id"]) > 0
    doc_id = r.body["_id"]
    r = call("POST", f"/idx/_update/{doc_id}", {"doc": {"y": 2}})
    assert r.body["result"] == "updated"
    src = call("GET", f"/idx/_doc/{doc_id}").body["_source"]
    assert src == {"x": 1, "y": 2}
    # noop detection
    r = call("POST", f"/idx/_update/{doc_id}", {"doc": {"y": 2}})
    assert r.body["result"] == "noop"
    # upsert on missing
    r = call("POST", "/idx/_update/zzz", {"doc": {"a": 1}, "doc_as_upsert": True})
    assert r.body["result"] == "created"
    r = call("POST", "/idx/_update/missing2", {"doc": {"a": 1}})
    assert r.status == 404


def test_bulk_and_search_flow(api):
    call, _ = api
    bulk = "\n".join([
        json.dumps({"index": {"_index": "lib", "_id": "1"}}),
        json.dumps({"title": "the quick brown fox", "year": 2001}),
        json.dumps({"index": {"_index": "lib", "_id": "2"}}),
        json.dumps({"title": "lazy dogs sleep", "year": 2005}),
        json.dumps({"create": {"_index": "lib", "_id": "3"}}),
        json.dumps({"title": "quick quick fox fox", "year": 2010}),
        json.dumps({"delete": {"_index": "lib", "_id": "2"}}),
        json.dumps({"update": {"_index": "lib", "_id": "1"}}),
        json.dumps({"doc": {"year": 2002}}),
    ]) + "\n"
    r = call("POST", "/_bulk", bulk, params={"refresh": "true"})
    assert r.status == 200
    assert not r.body["errors"]
    ops = [next(iter(item)) for item in r.body["items"]]
    assert ops == ["index", "index", "create", "delete", "update"]

    r = call("GET", "/lib/_search", {"query": {"match": {"title": "quick fox"}}})
    hits = r.body["hits"]["hits"]
    assert [h["_id"] for h in hits] == ["3", "1"]
    assert r.body["hits"]["total"]["value"] == 2

    r = call("GET", "/lib/_count")
    assert r.body["count"] == 2

    # bulk with error item
    bulk_err = "\n".join([
        json.dumps({"create": {"_index": "lib", "_id": "1"}}),
        json.dumps({"title": "dup"}),
    ]) + "\n"
    r = call("POST", "/_bulk", bulk_err)
    assert r.body["errors"] is True
    assert r.body["items"][0]["create"]["status"] == 409


def test_msearch(api):
    call, _ = api
    call("PUT", "/a/_doc/1", {"t": "alpha"}, params={"refresh": "true"})
    call("PUT", "/b/_doc/1", {"t": "beta"}, params={"refresh": "true"})
    body = "\n".join([
        json.dumps({"index": "a"}),
        json.dumps({"query": {"match_all": {}}}),
        json.dumps({"index": "b"}),
        json.dumps({"query": {"match": {"t": "beta"}}}),
        json.dumps({"index": "missing"}),
        json.dumps({"query": {"match_all": {}}}),
    ]) + "\n"
    r = call("POST", "/_msearch", body)
    rs = r.body["responses"]
    assert rs[0]["hits"]["total"]["value"] == 1
    assert rs[1]["hits"]["hits"][0]["_id"] == "1"
    assert rs[2]["status"] == 404


def test_multi_index_and_wildcard_search(api):
    call, _ = api
    call("PUT", "/logs-1/_doc/1", {"msg": "error one"}, params={"refresh": "true"})
    call("PUT", "/logs-2/_doc/2", {"msg": "error two"}, params={"refresh": "true"})
    r = call("GET", "/logs-*/_search", {"query": {"match": {"msg": "error"}}})
    assert r.body["hits"]["total"]["value"] == 2
    r = call("GET", "/_search", {"query": {"match_all": {}}})
    assert r.body["hits"]["total"]["value"] >= 2
    r = call("GET", "/_cat/indices")
    assert "logs-1" in r.body


def test_aliases(api):
    call, _ = api
    call("PUT", "/idx-v1/_doc/1", {"x": 1}, params={"refresh": "true"})
    r = call("POST", "/_aliases", {"actions": [{"add": {"index": "idx-v1", "alias": "current"}}]})
    assert r.body["acknowledged"]
    r = call("GET", "/current/_search", {"query": {"match_all": {}}})
    assert r.body["hits"]["total"]["value"] == 1
    r = call("GET", "/idx-v1/_alias")
    assert "current" in r.body["idx-v1"]["aliases"]
    call("POST", "/_aliases", {"actions": [{"remove": {"index": "idx-v1", "alias": "current"}}]})
    r = call("GET", "/current/_search", {"query": {"match_all": {}}})
    assert r.status == 404


def test_delete_by_query(api):
    call, _ = api
    for i in range(6):
        call("PUT", f"/dbq/_doc/{i}", {"n": i})
    call("POST", "/dbq/_refresh")
    r = call("POST", "/dbq/_delete_by_query", {"query": {"range": {"n": {"gte": 3}}}})
    assert r.body["deleted"] == 3
    assert call("GET", "/dbq/_count").body["count"] == 3


def test_analyze(api):
    call, _ = api
    r = call("POST", "/_analyze", {"analyzer": "standard", "text": "The Quick Fox"})
    assert [t["token"] for t in r.body["tokens"]] == ["the", "quick", "fox"]
    assert r.body["tokens"][1]["position"] == 1


def test_cluster_apis(api):
    call, node = api
    call("PUT", "/x", {"settings": {"number_of_shards": 1, "number_of_replicas": 0}})
    r = call("GET", "/_cluster/health")
    assert r.body["status"] in ("green", "yellow")
    assert r.body["number_of_nodes"] == 1
    r = call("GET", "/_cluster/state")
    assert "x" in r.body["metadata"]["indices"]
    r = call("GET", "/_nodes")
    assert r.body["_nodes"]["total"] == 1
    r = call("GET", "/_nodes/stats")
    assert "breakers" in r.body["nodes"][node.node_id]
    r = call("GET", "/_cat/health")
    assert "elasticsearch-tpu" in r.body
    r = call("GET", "/_cat/shards")
    assert "x 0 p STARTED" in r.body


def test_sharded_index_via_rest(api):
    call, _ = api
    call("PUT", "/big", {"settings": {"number_of_shards": 3, "number_of_replicas": 0}})
    for i in range(30):
        call("PUT", f"/big/_doc/{i}", {"body": f"word{i % 5} filler"})
    call("POST", "/big/_refresh")
    r = call("GET", "/big/_count")
    assert r.body["count"] == 30
    r = call("GET", "/big/_search", {"query": {"match": {"body": "word3"}}, "size": 20})
    assert r.body["hits"]["total"]["value"] == 6
    assert r.body["_shards"]["total"] == 3
    r = call("GET", "/big/_stats")
    assert r.body["_all"]["primaries"]["docs"]["count"] == 30


def test_error_shapes(api):
    call, _ = api
    r = call("GET", "/missing/_search", {"query": {"match_all": {}}})
    assert r.status == 404
    assert r.body["error"]["type"] == "index_not_found_exception"
    call("PUT", "/e/_doc/1", {"a": 1}, params={"refresh": "true"})
    r = call("GET", "/e/_search", {"query": {"bad_query": {}}})
    assert r.status == 400
    assert r.body["error"]["type"] == "parsing_exception"


def test_real_http_roundtrip():
    import urllib.request

    node = Node()
    rc = RestController()
    register_handlers(node, rc)
    server = HttpServer(rc, port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def http(method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(base + path, data=data, method=method,
                                         headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req) as resp:
                    return resp.status, json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read() or b"{}")

        status, body = http("GET", "/")
        assert status == 200 and body["tagline"] == "You Know, for Search"
        status, body = http("PUT", "/h/_doc/1?refresh=true", {"t": "hello http"})
        assert status == 201
        status, body = http("POST", "/h/_search", {"query": {"match": {"t": "hello"}}})
        assert body["hits"]["total"]["value"] == 1
        status, _ = http("GET", "/nope/_doc/1")
        assert status == 404
    finally:
        server.stop()
        node.close()


def test_index_blocks_read_and_metadata_enforced(api):
    """index.blocks.read gates data reads, index.blocks.metadata gates
    mapping/settings access — and a metadata-blocked index must still
    accept a blocks-only settings update so the block can be lifted
    (ref: TransportUpdateSettingsAction.checkBlock)."""
    call, _ = api
    assert call("PUT", "/b", {"mappings": {
        "properties": {"t": {"type": "text"}}}}).status == 200
    assert call("PUT", "/b/_doc/1", {"t": "hello world"}).status == 201
    call("POST", "/b/_refresh")

    assert call("PUT", "/b/_settings",
                {"index.blocks.read": True}).status == 200
    for method, path, body in [
            ("GET", "/b/_doc/1", None),
            ("POST", "/b/_search", {"query": {"match_all": {}}}),
            ("POST", "/b/_count", None),
            ("POST", "/b/_mget", {"ids": ["1"]})]:
        r = call(method, path, body)
        assert r.status == 403, (method, path, r.body)
        assert "cluster_block_exception" in json.dumps(r.body)
    # a read block does NOT gate writes
    assert call("PUT", "/b/_doc/2", {"t": "two"}).status == 201
    assert call("PUT", "/b/_settings",
                {"index.blocks.read": False}).status == 200
    assert call("GET", "/b/_doc/1").status == 200

    assert call("PUT", "/b/_settings",
                {"index.blocks.metadata": True}).status == 200
    assert call("GET", "/b/_mapping").status == 403
    assert call("GET", "/b/_settings").status == 403
    assert call("PUT", "/b/_mapping",
                {"properties": {"x": {"type": "keyword"}}}).status == 403
    # non-block settings updates are refused while metadata-blocked...
    assert call("PUT", "/b/_settings",
                {"index.refresh_interval": "1s"}).status == 403
    # ...but the block itself can always be lifted
    assert call("PUT", "/b/_settings",
                {"index.blocks.metadata": False}).status == 200
    assert call("GET", "/b/_mapping").status == 200

"""Block-max culled serving path: exactness vs the exhaustive SPMD path.

The culled two-pass executor must return IDENTICAL top-k (scores and docs)
to scoring every block — the parity bar BASELINE.md sets ("identical top-10
hits"). Exercised over Zipfian corpora where culling actually skips most
blocks, on single-shard and multi-shard meshes, with hot (dense-column) and
cold terms mixed.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.segment import build_field_postings
from elasticsearch_tpu.parallel import (
    build_stacked_bm25, make_mesh, prepare_query_blocks, sharded_bm25_topk,
)
from elasticsearch_tpu.parallel.blockmax import BlockMaxBM25

VOCAB = 300
N_DOCS = 3000


def zipf_corpus(rng, n_docs, n_shards):
    probs = 1.0 / np.arange(1, VOCAB + 1) ** 1.07
    probs /= probs.sum()
    lens = rng.integers(4, 40, size=n_docs).astype(np.int64)
    terms = rng.choice(VOCAB, size=int(lens.sum()), p=probs)
    shard_of = rng.integers(0, n_shards, size=n_docs)
    names = [f"t{i}" for i in range(VOCAB)]
    segments = []
    for s in range(n_shards):
        mask = shard_of == s
        doc_lens = lens[mask]
        # token -> local doc ord
        tok_doc_global = np.repeat(np.arange(n_docs), lens)
        tok_mask = mask[tok_doc_global]
        local_ord = np.cumsum(mask) - 1
        tok_docs = local_ord[tok_doc_global[tok_mask]]
        fp = build_field_postings("body", doc_lens, tok_docs.astype(np.int64),
                                  terms[tok_mask].astype(np.int64), names)

        class _Seg:
            pass

        seg = _Seg()
        seg.n_docs = int(mask.sum())
        seg.postings = {"body": fp}
        segments.append(seg)
    return segments


def draw_queries(rng, n, n_terms=(1, 2, 3)):
    qprobs = 1.0 / np.arange(1, VOCAB + 1) ** 1.07
    qprobs /= qprobs.sum()
    out = []
    for _ in range(n):
        m = int(rng.choice(n_terms))
        out.append([f"t{t}" for t in rng.choice(VOCAB, size=m, p=qprobs,
                                                replace=False)])
    return out


def assert_topk_equal(ref, got, q, queries):
    """Exact-parity assertion: same scores; same (shard, ord) ORDER wherever
    adjacent scores are separated beyond f32 noise (both paths tie-break by
    (shard, ord), so only float-rounding near-ties may legitimately swap)."""
    ref_s, ref_sh, ref_o = ref
    got_s, got_sh, got_o = got
    np.testing.assert_allclose(got_s[q], ref_s[q], rtol=2e-5, atol=2e-5)
    valid = ref_s[q] > -np.inf
    ref_ids = list(zip(ref_sh[q][valid], ref_o[q][valid]))
    got_ids = list(zip(got_sh[q][valid], got_o[q][valid]))
    s = ref_s[q][valid]
    gaps = np.abs(np.diff(s)) > 2e-5 * np.abs(s[:-1]) + 2e-5
    if gaps.all():
        assert got_ids == ref_ids, f"query {q}: {queries[q]}"
    else:  # near-ties may permute across float noise; sets must still match
        assert set(got_ids) == set(ref_ids), f"query {q}: {queries[q]}"


@pytest.mark.parametrize("n_shards,dp", [(1, 1), (4, 2)])
def test_blockmax_matches_exhaustive(n_shards, dp):
    rng = np.random.default_rng(17)
    segments = zipf_corpus(rng, N_DOCS, n_shards)
    mesh = make_mesh(n_shards * dp, dp=dp)
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    serving = BlockMaxBM25(stacked, mesh)
    queries = draw_queries(rng, 40)

    ref_s, ref_sh, ref_o = sharded_bm25_topk(
        mesh, stacked, *prepare_query_blocks(stacked, queries), k=10)
    got_s, got_sh, got_o = serving.search(queries, k=10)

    for q in range(len(queries)):
        assert_topk_equal((ref_s, ref_sh, ref_o), (got_s, got_sh, got_o),
                          q, queries)


def test_blockmax_culls_blocks():
    """A frequent term's low-impact blocks must be dropped when a rare term
    sets the bar — the dynamic-pruning behavior SURVEY §5.7 calls for."""
    n_docs = 20_000
    lens = np.full(n_docs, 10, np.int64)
    tok_docs, tok_terms = [], []
    rng = np.random.default_rng(11)
    for d in range(n_docs):
        toks = []
        if d % 8 == 0 and d < 19200:          # 2400 docs with "common" (tf 1)
            toks.append(0)
        if 960 <= d < 1088 and d % 8 == 0:    # 16 of them with tf 8
            toks.extend([0] * 7)
        if 4000 <= d < 4020:                  # 20 docs with "rare"
            toks.append(1)
        while len(toks) < 10:
            toks.append(2 + int(rng.integers(0, 5000)))
        tok_docs.extend([d] * 10)
        tok_terms.extend(toks[:10])
    names = ["common", "rare"] + [f"f{i}" for i in range(5000)]
    from elasticsearch_tpu.index.segment import build_field_postings

    fp = build_field_postings("body", lens, np.asarray(tok_docs, np.int64),
                              np.asarray(tok_terms, np.int64), names)

    class _Seg:
        pass

    seg = _Seg()
    seg.n_docs = n_docs
    seg.postings = {"body": fp}
    mesh = make_mesh(1, dp=1)
    stacked = build_stacked_bm25([seg], "body", mesh=mesh)
    serving = BlockMaxBM25(stacked, mesh)

    scores, _, _ = serving.search([["common", "rare"]], k=10)
    mc = serving._terms["common"]
    assert mc.hot_slot < 0, "common unexpectedly classified hot"
    n_blocks = len(mc.blocks[0].ids)
    assert n_blocks >= 15
    sel, max_total = serving._select(
        [[("common", 1.0), ("rare", 1.0)]],
        np.asarray([scores[0][-1]], np.float32))
    kept = int(sel[0]["common"][0].sum())
    # only the tf-8 block(s) and the block(s) overlapping rare's doc range
    # may survive; the tf-1 bulk must be culled
    assert kept < n_blocks // 2, f"kept {kept} of {n_blocks} common blocks"


def test_fast_postings_builder_matches_slow():
    """build_field_postings must agree with the per-doc SegmentBuilder."""
    from elasticsearch_tpu.index.segment import SegmentBuilder
    from elasticsearch_tpu.mapper.mapper_service import LuceneDoc

    rng = np.random.default_rng(5)
    n_docs, vocab = 200, 30
    lens = rng.integers(1, 20, size=n_docs).astype(np.int64)
    terms = rng.choice(vocab, size=int(lens.sum()))
    names = [f"t{i:03d}" for i in range(vocab)]   # zero-padded: sorted order

    fast = build_field_postings(
        "body", lens, np.repeat(np.arange(n_docs), lens).astype(np.int64),
        terms.astype(np.int64), names)

    builder = SegmentBuilder()
    off = 0
    for i in range(n_docs):
        n = int(lens[i])
        vals, counts = np.unique(terms[off:off + n], return_counts=True)
        off += n
        doc = LuceneDoc(doc_id=str(i), source={})
        doc.inverted["body"] = [(names[v], list(range(int(c))))
                                for v, c in zip(vals, counts)]
        doc.field_lengths["body"] = n
        builder.add(doc, seq_no=i)
    slow = builder.build().postings["body"]

    used = [i for i in range(vocab) if fast.doc_freq[i] > 0]
    assert [names[i] for i in used] == slow.terms
    for i, t in zip(used, slow.terms):
        np.testing.assert_array_equal(fast.term_block_ids(names[i]) > 0,
                                      slow.term_block_ids(t) > 0)
        o_f, o_s = fast.term_to_ord[t], slow.term_to_ord[t]
        assert fast.doc_freq[o_f] == slow.doc_freq[o_s]
        assert fast.total_term_freq[o_f] == slow.total_term_freq[o_s]
        fb = fast.term_block_ids(t)
        sb = slow.term_block_ids(t)
        np.testing.assert_array_equal(fast.block_docs[fb], slow.block_docs[sb])
        np.testing.assert_array_equal(fast.block_tfs[fb], slow.block_tfs[sb])
        np.testing.assert_array_equal(fast.block_max_tf[fb], slow.block_max_tf[sb])
    np.testing.assert_array_equal(fast.doc_len, slow.doc_len)


def _brute_bool(segments, stacked, spec, k):
    """Dense reference: accumulate scores + coverage per shard, filter, rank."""
    from elasticsearch_tpu.ops import bm25_idf
    from elasticsearch_tpu.parallel.blockmax import _host_block_scores

    must = [(t, b, True) for t, b in spec.get("must", ())]
    must += [(t, 0.0, True) for t in spec.get("filter", ())]
    should = [(t, b, False) for t, b in spec.get("should", ())]
    nm = sum(1 for _ in must)
    out = []
    df_of = {}
    for t, _, _ in must + should:
        df_of[t] = sum(int(fp.doc_freq[fp.term_to_ord[t]])
                       for fp in (s.postings["body"] for s in segments)
                       if t in fp.term_to_ord)
    for si, seg in enumerate(segments):
        fp = seg.postings["body"]
        bs = _host_block_scores(fp, stacked.avgdl)
        scores = np.zeros(seg.n_docs, np.float32)
        cover = np.zeros(seg.n_docs, np.int32)
        for t, b, req in must + should:
            if df_of[t] == 0:
                continue
            o = fp.term_to_ord.get(t)
            if o is None:
                continue
            w = bm25_idf(stacked.total_docs, df_of[t]) * b
            lo, hi = int(fp.post_start[o]), int(fp.post_start[o + 1])
            docs = fp.post_doc[lo:hi]
            start, cnt = int(fp.block_start[o]), int(fp.block_count[o])
            lane = bs[start:start + cnt].ravel()
            ld = fp.block_docs[start:start + cnt].ravel()
            nz = lane > 0
            scores[ld[nz]] += (w * lane[nz]).astype(np.float32)
            if req:
                cover[docs] += 1
        ok = (cover == nm) & (scores > 0)
        docs = np.nonzero(ok)[0]
        if len(docs):
            sel = np.lexsort((docs, -scores[docs]))[:k]
            out.extend((float(scores[docs[i]]), si, int(docs[i])) for i in sel)
    out.sort(key=lambda x: (-x[0], x[1], x[2]))
    return out[:k]


@pytest.mark.parametrize("n_shards,host_conj_df", [(1, 0), (2, 0),
                                                   (1, 1 << 16)])
def test_search_bool_matches_brute_force(n_shards, host_conj_df, monkeypatch):
    """host_conj_df=0 forces every query onto the DEVICE program; the
    default threshold routes these small-df queries to the host sparse
    conjunction — both must match the brute-force reference exactly."""
    import elasticsearch_tpu.parallel.blockmax as bm

    monkeypatch.setattr(bm, "_HOST_CONJ_DF", host_conj_df)
    rng = np.random.default_rng(41)
    segments = zipf_corpus(rng, N_DOCS, n_shards)
    mesh = make_mesh(n_shards, dp=1)
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    serving = BlockMaxBM25(stacked, mesh)

    qprobs = 1.0 / np.arange(1, VOCAB + 1) ** 1.07
    qprobs /= qprobs.sum()
    queries = []
    for _ in range(30):
        terms = [f"t{t}" for t in rng.choice(VOCAB, size=5, p=qprobs,
                                             replace=False)]
        queries.append({
            "must": [(terms[0], 1.0), (terms[1], float(rng.choice([1.0, 2.0])))],
            "should": [(terms[2], 1.0), (terms[3], 1.0)],
            "filter": [terms[4]] if rng.random() < 0.5 else [],
        })
    # hot-term cases: t0/t1 are stopword-grade under the Zipf draw
    queries.append({"must": [("t0", 1.0)], "should": [("t5", 1.0)]})
    queries.append({"must": [("t0", 1.0), ("t1", 1.0)], "filter": ["t2"]})
    queries.append({"must": [("t200", 1.0)], "filter": ["t0"]})
    queries.append({"must": [("absent-term", 1.0), ("t1", 1.0)]})

    got_s, got_sh, got_o = serving.search_bool(queries, k=10)
    for qi_, spec in enumerate(queries):
        want = _brute_bool(segments, stacked, spec, 10)
        got = [(float(got_s[qi_][j]), int(got_sh[qi_][j]), int(got_o[qi_][j]))
               for j in range(10) if got_s[qi_][j] > 0]
        assert len(got) == len(want), f"query {qi_}: {spec}"
        for (es, esh, eo), (gs, gsh, go) in zip(want, got):
            assert abs(es - gs) <= 2e-5 * abs(es) + 2e-5, f"query {qi_}"
        # order equality wherever adjacent scores separated beyond f32 noise
        ws = np.asarray([w[0] for w in want])
        gaps = np.abs(np.diff(ws)) > 2e-5 * np.abs(ws[:-1]) + 2e-5
        if gaps.all():
            assert [(sh, o) for _, sh, o in want] == \
                [(sh, o) for _, sh, o in got], f"query {qi_}: {spec}"
        else:
            assert {(sh, o) for _, sh, o in want} == \
                {(sh, o) for _, sh, o in got}, f"query {qi_}: {spec}"


def test_search_bool_overflow_fallback(monkeypatch):
    import elasticsearch_tpu.parallel.blockmax as bm

    rng = np.random.default_rng(43)
    segments = zipf_corpus(rng, N_DOCS, 1)
    mesh = make_mesh(1, dp=1)
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    queries = [{"must": [("t10", 1.0)], "should": [("t20", 1.0)]}
               for _ in range(3)]
    want = [_brute_bool(segments, stacked, q, 10) for q in queries]
    monkeypatch.setattr(bm, "_MAX_BUCKET", 4)
    serving = BlockMaxBM25(stacked, mesh)
    got_s, got_sh, got_o = serving.search_bool(queries, k=10)
    for qi_, w in enumerate(want):
        got = [(float(got_s[qi_][j]), int(got_sh[qi_][j]), int(got_o[qi_][j]))
               for j in range(10) if got_s[qi_][j] > 0]
        assert [(sh, o) for _, sh, o in w] == [(sh, o) for _, sh, o in got]


def test_overflow_path_matches_exhaustive(monkeypatch):
    """Queries whose surviving blocks exceed the largest dispatch bucket must
    take the chunked scatter-add overflow path and stay EXACT (ADVICE r2: the
    bucketed path used to silently truncate kept blocks). Forced by shrinking
    the bucket ladder so ordinary queries overflow."""
    import elasticsearch_tpu.parallel.blockmax as bm

    rng = np.random.default_rng(23)
    segments = zipf_corpus(rng, N_DOCS, 2)
    mesh = make_mesh(2, dp=1)
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    queries = draw_queries(rng, 12)

    ref_s, ref_sh, ref_o = sharded_bm25_topk(
        mesh, stacked, *prepare_query_blocks(stacked, queries), k=10)

    monkeypatch.setattr(bm, "_GROUP_SHAPES", [(8, 512)])
    monkeypatch.setattr(bm, "_MAX_BUCKET", 8)
    monkeypatch.setattr(bm, "_OVERFLOW_CHUNK", 16)
    serving = BlockMaxBM25(stacked, mesh)
    got_s, got_sh, got_o = serving.search(queries, k=10)

    for q in range(len(queries)):
        assert_topk_equal((ref_s, ref_sh, ref_o), (got_s, got_sh, got_o),
                          q, queries)

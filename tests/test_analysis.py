from elasticsearch_tpu.analysis import (
    AnalysisRegistry,
    KeywordAnalyzer,
    SimpleAnalyzer,
    StandardAnalyzer,
    StopAnalyzer,
    WhitespaceAnalyzer,
)


def test_standard_analyzer_lowercases_and_splits():
    a = StandardAnalyzer()
    assert a.terms("The QUICK Brown-Fox, jumps!") == ["the", "quick", "brown", "fox", "jumps"]


def test_standard_analyzer_positions_and_offsets():
    a = StandardAnalyzer()
    toks = a.tokenize("Hello, World")
    assert [(t.term, t.position) for t in toks] == [("hello", 0), ("world", 1)]
    assert (toks[1].start_offset, toks[1].end_offset) == (7, 12)


def test_whitespace_analyzer_preserves_case():
    assert WhitespaceAnalyzer().terms("Foo BAR baz") == ["Foo", "BAR", "baz"]


def test_keyword_analyzer_single_token():
    assert KeywordAnalyzer().terms("New York City") == ["New York City"]
    assert KeywordAnalyzer().terms("") == []


def test_simple_analyzer_letters_only():
    assert SimpleAnalyzer().terms("abc123def") == ["abc", "def"]


def test_stop_analyzer_removes_stopwords():
    assert StopAnalyzer().terms("the quick fox") == ["quick", "fox"]


def test_numbers_tokenized_by_standard():
    assert StandardAnalyzer().terms("ipv4 10.0.0.1 port 9200") == ["ipv4", "10", "0", "0", "1", "port", "9200"]


def test_registry_builtin_and_custom():
    reg = AnalysisRegistry({
        "my_custom": {"tokenizer": "whitespace", "filter": ["lowercase"]},
        "folded": {"tokenizer": "standard", "filter": ["lowercase", "asciifolding"]},
    })
    assert reg.get("standard").terms("A b") == ["a", "b"]
    assert reg.get("my_custom").terms("Foo-Bar BAZ") == ["foo-bar", "baz"]
    assert reg.get("folded").terms("Café Über") == ["cafe", "uber"]


def test_unicode_text():
    a = StandardAnalyzer()
    assert a.terms("Москва 北京 café") == ["москва", "北京", "café"]

"""Request cache + per-segment filter-mask cache (VERDICT r2 missing #9)."""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture()
def svc():
    meta = IndexMetadata(index="c", uuid="u", settings=Settings({}), mappings={
        "properties": {"body": {"type": "text"}, "n": {"type": "integer"},
                       "tag": {"type": "keyword"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(2)
    for i in range(100):
        svc.index_doc(str(i), {"body": f"w{rng.integers(0, 20)} filler",
                               "n": i, "tag": f"t{i % 4}"})
    svc.refresh()
    yield svc
    svc.close()


def test_request_cache_hit_and_invalidation(svc):
    body = {"query": {"match": {"body": "w3"}}, "size": 0,
            "aggs": {"m": {"max": {"field": "n"}}}, "track_total_hits": True}
    r1 = svc.search(body)
    assert svc.request_cache_stats == {"hits": 0, "misses": 1}
    r2 = svc.search(body)
    assert svc.request_cache_stats["hits"] == 1
    assert r2["aggregations"] == r1["aggregations"]
    assert r2["hits"]["total"] == r1["hits"]["total"]
    # a write + refresh changes the searcher version -> miss, fresh result
    svc.index_doc("new", {"body": "w3 filler", "n": 999})
    svc.refresh()
    r3 = svc.search(body)
    assert svc.request_cache_stats["misses"] == 2
    assert r3["hits"]["total"]["value"] == r1["hits"]["total"]["value"] + 1
    assert r3["aggregations"]["m"]["value"] == 999.0


def test_sized_requests_not_cached(svc):
    body = {"query": {"match": {"body": "w3"}}, "size": 5}
    svc.search(body)
    svc.search(body)
    assert svc.request_cache_stats["hits"] == 0


def test_cached_response_isolated_from_mutation(svc):
    body = {"query": {"match_all": {}}, "size": 0, "track_total_hits": True}
    r1 = svc.search(body)
    r1["hits"]["total"]["value"] = -1   # caller mutates its copy
    r2 = svc.search(body)
    assert r2["hits"]["total"]["value"] != -1


def test_filter_mask_cache_reused(svc):
    searcher = svc.shards[0].acquire_searcher()
    seg = searcher.views[0].segment
    before = [k for k in seg._device if k.startswith("qcache:")]
    body = {"query": {"bool": {"must": [{"match": {"body": "w3"}}],
                               "filter": [{"range": {"n": {"gte": 10}}},
                                          {"term": {"tag": "t1"}}]}}}
    svc._search_dense(body)
    after = [k for k in seg._device if k.startswith("qcache:")]
    assert len(after) >= len(before) + 1   # range mask cached
    svc._search_dense(body)                # reuse, no growth
    assert [k for k in seg._device
            if k.startswith("qcache:")] == after
    # results correct across the cache
    r = svc._search_dense(body)
    for h in r["hits"]["hits"]:
        assert int(h["_source"]["n"]) >= 10 and h["_source"]["tag"] == "t1"

"""Cluster task plane (PR 11): cross-node task trees, ban-propagated
cancellation, hot-threads fan-out, partial answers over dead peers.

Runs on the deterministic in-process harness (LocalNodeChannels): every
fan-out, ban, and reap crosses the same transport the data path uses.
"""

import threading
import time

import pytest

from elasticsearch_tpu.cluster_node import form_local_cluster
from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError, IllegalArgumentError,
)
from elasticsearch_tpu.tasks import TaskCancelledError

MAPPINGS = {"properties": {"body": {"type": "text"},
                           "n": {"type": "integer"}}}


def two_nodes(data_path=None):
    return form_local_cluster(["a", "b"], data_path=data_path)


def fill(node, index="docs", shards=2, docs=40):
    node.create_index(index, {
        "settings": {"number_of_shards": shards, "number_of_replicas": 0},
        "mappings": MAPPINGS})
    node.bulk(index, [{"op": "index", "id": str(i),
                       "source": {"body": f"w{i % 5} common", "n": i}}
                      for i in range(docs)])
    node.refresh(index)


class _SlowShard:
    """Stalls node `b`'s shard-query handler until released, signalling
    when the first query arrives — a deterministic in-flight window."""

    def __init__(self, node, hold_s=6.0):
        self.node = node
        self.entered = threading.Event()
        self.release = threading.Event()
        self.hold_s = hold_s
        self._orig = node.search_action._shard_query_inner

    def __enter__(self):
        orig = self._orig

        def slow(req):
            self.entered.set()
            self.release.wait(self.hold_s)
            return orig(req)

        self.node.search_action._shard_query_inner = slow
        return self

    def __exit__(self, *exc):
        self.release.set()
        self.node.search_action._shard_query_inner = self._orig


def _search_bg(node, index="docs", body=None):
    out = {}

    def run():
        try:
            out["r"] = node.search(index, body or {
                "query": {"match": {"body": "common"}}, "size": 5})
        except BaseException as e:  # noqa: BLE001 — asserted by callers
            out["e"] = e

    t = threading.Thread(target=run)
    t.start()
    return t, out


def test_cross_node_tree_detailed_with_trace_linkage():
    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a)
    with _SlowShard(b) as slow:
        # profile forces the flight recorder on: the tree must carry the
        # coordinator's trace id down to every remote shard child
        t, out = _search_bg(a, body={
            "query": {"match": {"body": "common"}}, "size": 5,
            "profile": True})
        assert slow.entered.wait(5)
        listing = a.task_plane.list(detailed=True)
        slow.release.set()
        t.join(timeout=30)
    assert "e" not in out
    tasks = {tid: d for sec in listing["nodes"].values()
             for tid, d in sec["tasks"].items()}
    parents = {tid: d for tid, d in tasks.items()
               if d["action"] == "indices:data/read/search"
               and d.get("parent_task_id") is None}
    assert len(parents) == 1
    ptid, parent = next(iter(parents.items()))
    assert ptid.startswith("a:")
    children = {tid: d for tid, d in tasks.items()
                if d.get("parent_task_id") == ptid}
    # node b's shard-query child is linked to node a's coordinator
    assert any(tid.startswith("b:") for tid in children)
    for d in children.values():
        assert d["action"].startswith("indices:data/read/search[phase/")
        assert d["headers"]["trace_id"] == parent["headers"]["trace_id"]
        assert d["status"]["phase"] in ("query", "fetch")
    assert parent["running_time_in_nanos"] > 0
    assert parent["cancellable"] is True


def test_group_by_parents_nests_remote_children():
    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a)
    with _SlowShard(b) as slow:
        t, out = _search_bg(a)
        assert slow.entered.wait(5)
        listing = a.task_plane.list(detailed=True, group_by="parents")
        flat = a.task_plane.list(group_by="none")
        slow.release.set()
        t.join(timeout=30)
    assert "e" not in out
    roots = listing["tasks"]
    parent = next(d for d in roots.values()
                  if d.get("parent_task_id") is None)
    kids = parent.get("children", [])
    assert any(d["node"] == "b" for d in kids)
    assert isinstance(flat["tasks"], list) and len(flat["tasks"]) >= 2


def test_list_filters_actions_nodes_and_parent():
    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a)
    with _SlowShard(b) as slow:
        t, out = _search_bg(a)
        assert slow.entered.wait(5)
        only_b = a.task_plane.list(nodes="b")
        only_search = a.task_plane.list(actions="indices:data/read/search")
        parent_tid = next(
            tid for sec in a.task_plane.list()["nodes"].values()
            for tid, d in sec["tasks"].items()
            if d.get("parent_task_id") is None)
        by_parent = a.task_plane.list(parent_task_id=parent_tid)
        slow.release.set()
        t.join(timeout=30)
    assert "e" not in out
    assert set(only_b["nodes"]) == {"b"}
    for sec in only_search["nodes"].values():
        for d in sec["tasks"].values():
            assert d["action"] == "indices:data/read/search"
    for sec in by_parent["nodes"].values():
        for d in sec["tasks"].values():
            assert d["parent_task_id"] == parent_tid


def test_dead_node_yields_partial_list_with_node_failures():
    nodes, store, channels = two_nodes()
    a, b = nodes
    channels.kill("b")
    listing = a.task_plane.list()
    assert set(listing["nodes"]) == {"a"}
    fails = listing["node_failures"]
    assert [f["node_id"] for f in fails] == ["b"]
    assert fails[0]["type"] == "failed_node_exception"
    assert fails[0]["caused_by"]["type"] == "node_not_connected_exception"
    channels.revive("b")
    assert "node_failures" not in a.task_plane.list()


def test_task_id_routing_cross_node():
    nodes, store, channels = two_nodes()
    a, b = nodes
    t = b.tasks.register("indices:data/read/search", "remote probe")
    got = a.task_plane.get(f"b:{t.id}")          # routed to the owner
    assert got["task"]["description"] == "remote probe"
    assert got["task"]["node"] == "b"
    with pytest.raises(IllegalArgumentError):
        a.task_plane.get("zzz:notanum")           # malformed: 400 first
    with pytest.raises(ElasticsearchTpuError) as ei:
        a.task_plane.get("ghost:123")             # unknown node: 404
    assert ei.value.status == 404
    channels.kill("b")
    with pytest.raises(ElasticsearchTpuError) as ei:
        a.task_plane.get(f"b:{t.id}")             # dead node: 404
    assert ei.value.status == 404
    channels.revive("b")
    b.tasks.unregister(t)


def test_cross_node_cancel_bans_children_within_one_boundary():
    """The acceptance criterion: cancelling the coordinator on node a
    kills node b's shard child at its next dispatch boundary, and the ban
    cancels a not-yet-registered child on arrival."""
    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a)
    with _SlowShard(b) as slow:
        t, out = _search_bg(a)
        assert slow.entered.wait(5)
        parent_tid = next(
            tid for sec in a.task_plane.list()["nodes"].values()
            for tid, d in sec["tasks"].items()
            if d.get("parent_task_id") is None)
        resp = a.task_plane.cancel(parent_tid, reason="test cancel")
        assert parent_tid in resp["nodes"]["a"]["tasks"]
        # the ban crossed the wire before the child's next boundary
        assert b.tasks.stats()["bans_received"] == 1
        banned_children = [d for d in b.tasks.list()
                           if d.parent_task_id == parent_tid]
        assert all(c.is_cancelled for c in banned_children)
        slow.release.set()
        t.join(timeout=30)
    assert isinstance(out.get("e"), TaskCancelledError)
    assert a.tasks.stats()["bans_propagated"] >= 1
    # ban-on-arrival: a racing child registering AFTER the cancel reaches
    # node b is born cancelled (TaskCancellationService semantics)
    late = b.tasks.register("indices:data/read/search[phase/query]",
                            parent_task_id=parent_tid)
    assert late.is_cancelled
    b.tasks.unregister(late)


def test_cancel_wait_for_completion_drains_descendants():
    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a)
    with _SlowShard(b, hold_s=0.3) as slow:
        t, out = _search_bg(a)
        assert slow.entered.wait(5)
        parent_tid = next(
            tid for sec in a.task_plane.list()["nodes"].values()
            for tid, d in sec["tasks"].items()
            if d.get("parent_task_id") is None)
        a.task_plane.cancel(parent_tid, wait_for_completion=True,
                            timeout_ms=5000)
        # after the drain returns no descendant survives anywhere
        for node in (a, b):
            assert not [x for x in node.tasks.list()
                        if x.parent_task_id == parent_tid]
        t.join(timeout=30)
    assert isinstance(out.get("e"), TaskCancelledError)


def test_node_left_reaps_orphans_by_ban():
    nodes, store, channels = two_nodes()
    a, b = nodes
    orphan = b.tasks.register("indices:data/read/search[phase/query]",
                              parent_task_id="c:42")
    a.task_plane.broadcast_reap("c")
    assert orphan.is_cancelled
    assert b.tasks.stats()["orphans_reaped"] == 1
    # the node-wide ban also kills late registrations from the dead node
    late = b.tasks.register("indices:data/read/search[phase/fetch]",
                            parent_task_id="c:7")
    assert late.is_cancelled
    b.tasks.unregister(orphan)
    b.tasks.unregister(late)


def test_cancelled_round_leaves_identical_rerun():
    """No-cancel purity at cluster level: after a cancelled search, an
    identical fresh search returns exactly what a quiet cluster returns."""
    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a)
    body = {"query": {"match": {"body": "common"}}, "size": 10,
            "track_total_hits": True}
    quiet = a.search("docs", body)
    with _SlowShard(b) as slow:
        t, out = _search_bg(a, body=body)
        assert slow.entered.wait(5)
        parent_tid = next(
            tid for sec in a.task_plane.list()["nodes"].values()
            for tid, d in sec["tasks"].items()
            if d.get("parent_task_id") is None)
        a.task_plane.cancel(parent_tid)
        slow.release.set()
        t.join(timeout=30)
    assert isinstance(out.get("e"), TaskCancelledError)
    rerun = a.search("docs", body)
    assert rerun["hits"] == quiet["hits"]
    assert rerun["_shards"] == quiet["_shards"]


def test_hot_threads_fans_out_and_reports_dead_peers():
    nodes, store, channels = two_nodes()
    a, b = nodes
    report = a.task_plane.hot_threads()
    assert "::: {a}" in report and "::: {b}" in report
    assert "thread [" in report
    channels.kill("b")
    partial = a.task_plane.hot_threads()
    assert "::: {a}" in partial
    assert "failed to fetch hot_threads" in partial
    channels.revive("b")


def test_cat_tasks_rows_cover_cluster():
    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a)
    with _SlowShard(b) as slow:
        t, out = _search_bg(a)
        assert slow.entered.wait(5)
        rows = a.task_plane.cat_rows()
        slow.release.set()
        t.join(timeout=30)
    assert "e" not in out
    assert any("indices:data/read/search " in r and " a" in r for r in rows)
    assert any(r.startswith("indices:data/read/search[phase/")
               for r in rows)


def test_bulk_registers_coordinator_and_shard_children():
    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a, docs=4)
    before_b = b.tasks.stats()["registered"]
    before_a = a.tasks.stats()["registered"]
    a.bulk("docs", [{"op": "index", "id": f"x{i}",
                     "source": {"body": "late", "n": 100 + i}}
                    for i in range(8)])
    assert a.tasks.stats()["registered"] > before_a
    # node b holds one of the two shards: its bulk child registered there
    assert b.tasks.stats()["registered"] > before_b
    assert not a.tasks.list() and not b.tasks.list()   # all drained


def test_running_time_is_monotonic_and_wall_clock_start():
    nodes, _, _ = two_nodes()
    a, _b = nodes
    t = a.tasks.register("indices:data/read/search", "clock probe")
    wall = time.time() * 1000
    d1 = t.to_dict()
    time.sleep(0.02)
    d2 = t.to_dict()
    assert d2["running_time_in_nanos"] > d1["running_time_in_nanos"]
    assert d1["running_time_in_nanos"] >= 0
    assert abs(d1["start_time_in_millis"] - wall) < 60_000
    a.tasks.unregister(t)


def test_task_duration_histogram_and_stats_sections():
    from elasticsearch_tpu.common import metrics

    nodes, store, channels = two_nodes()
    a, b = nodes
    fill(a)
    a.search("docs", {"query": {"match": {"body": "common"}}})
    s = metrics.summary("task_duration.search")
    assert s and s["count"] >= 1
    st = a.tasks.stats()
    for key in ("registered", "completed", "cancelled", "bans_propagated",
                "bans_received", "orphans_reaped", "bans_active", "current"):
        assert key in st

"""Segment build + device BM25 scoring parity vs a naive host reference."""

import math

import numpy as np
import pytest

from elasticsearch_tpu.index import BLOCK, SegmentBuilder
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.ops import (
    bm25_idf,
    bm25_scatter_scores,
    constant_scatter_mask,
    knn_top_k,
    masked_top_k,
    pad_block_ids,
)

DOCS = [
    "the quick brown fox jumps over the lazy dog",
    "quick quick quick repetition of quick terms",
    "a completely unrelated document about jax and tpus",
    "the lazy dog sleeps all day the dog dreams",
    "fox hunting was banned in the united kingdom",
    "tpus accelerate matrix multiplication for search engines",
]


def build_segment(texts=DOCS, extra=None):
    svc = MapperService({"properties": {"body": {"type": "text"},
                                        "tag": {"type": "keyword"},
                                        "n": {"type": "long"},
                                        "v": {"type": "dense_vector", "dims": 8}}})
    b = SegmentBuilder()
    for i, t in enumerate(texts):
        src = {"body": t}
        if extra:
            src.update(extra[i])
        b.add(svc.parse(str(i), src), seq_no=i)
    return svc, b.build()


def naive_bm25(texts, query_terms, k1=1.2, b=0.75):
    """Reference scorer: classic Lucene BM25 over whitespace/lowercase terms."""
    tokenized = [t.lower().replace(",", "").split() for t in texts]
    n = len(texts)
    avgdl = sum(len(d) for d in tokenized) / n
    scores = np.zeros(n)
    for term in query_terms:
        df = sum(1 for d in tokenized if term in d)
        if df == 0:
            continue
        idf = math.log(1 + (n - df + 0.5) / (df + 0.5))
        for i, d in enumerate(tokenized):
            tf = d.count(term)
            if tf:
                scores[i] += idf * tf * (k1 + 1) / (tf + k1 * (1 - b + b * len(d) / avgdl))
    return scores


def device_scores_for_terms(seg, field, terms, k1=1.2, b=0.75):
    fp = seg.postings[field]
    n_field_docs, sum_dl = seg.field_stats(field)
    avgdl = sum_dl / max(n_field_docs, 1)
    block_docs, block_tfs, doc_len = seg.device(f"post:{field}")
    total = np.zeros(seg.n_docs, np.float32)
    for term in terms:
        ids = fp.term_block_ids(term)
        if len(ids) == 0:
            continue
        df, _ = seg.term_stats(field, term)
        idf = bm25_idf(seg.n_docs, df)
        padded = pad_block_ids(ids)
        idf_arr = np.zeros(len(padded), np.float32)
        idf_arr[: len(ids)] = idf
        s = bm25_scatter_scores(block_docs, block_tfs, doc_len, padded, idf_arr,
                                np.float32(avgdl), n_docs=seg.n_docs, k1=k1, b=b)
        total += np.asarray(s)
    return total


def test_block_layout_invariants():
    _, seg = build_segment()
    fp = seg.postings["body"]
    assert np.all(fp.block_docs[0] == 0) and np.all(fp.block_tfs[0] == 0)
    o = fp.term_to_ord["quick"]
    assert fp.doc_freq[o] == 2
    assert fp.total_term_freq[o] == 5  # 1 + 4
    assert fp.block_docs.shape[1] == BLOCK
    # doc lengths = token counts
    assert fp.doc_len[0] == 9
    assert seg.n_docs == len(DOCS)


def test_bm25_parity_single_term():
    _, seg = build_segment()
    got = device_scores_for_terms(seg, "body", ["quick"])
    want = naive_bm25(DOCS, ["quick"])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_bm25_parity_multi_term():
    _, seg = build_segment()
    for terms in (["the", "dog"], ["quick", "fox", "tpus"], ["absent"], ["a", "of", "search"]):
        got = device_scores_for_terms(seg, "body", terms)
        want = naive_bm25(DOCS, terms)
        np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=str(terms))


def test_bm25_parity_large_random_corpus():
    rng = np.random.default_rng(42)
    vocab = [f"w{i}" for i in range(50)]
    # Zipf-ish sampling so some terms span multiple 128-doc blocks
    probs = 1.0 / np.arange(1, len(vocab) + 1)
    probs /= probs.sum()
    texts = [" ".join(rng.choice(vocab, size=rng.integers(3, 30), p=probs)) for _ in range(700)]
    _, seg = build_segment(texts)
    fp = seg.postings["body"]
    assert int(fp.block_count.max()) >= 2  # multi-block terms exercised
    for terms in (["w0"], ["w0", "w7", "w33"], ["w1", "w2"]):
        got = device_scores_for_terms(seg, "body", terms)
        want = naive_bm25(texts, terms)
        np.testing.assert_allclose(got, want, rtol=2e-4, err_msg=str(terms))


def test_masked_top_k_order_and_validity():
    _, seg = build_segment()
    scores = device_scores_for_terms(seg, "body", ["the", "dog"])
    import jax.numpy as jnp

    mask = jnp.ones(seg.n_docs, bool)
    top_s, top_o, valid = masked_top_k(jnp.asarray(scores), mask, k=3)
    want = naive_bm25(DOCS, ["the", "dog"])
    assert list(np.asarray(top_o)[:2]) == list(np.argsort(-want)[:2])
    # mask out best doc
    mask = mask.at[int(top_o[0])].set(False)
    top_s2, top_o2, _ = masked_top_k(jnp.asarray(scores), mask, k=3)
    assert int(top_o2[0]) == int(top_o[1])
    # k > matches: invalid tail
    only = device_scores_for_terms(seg, "body", ["kingdom"])
    t, o, v = masked_top_k(jnp.asarray(only), jnp.asarray(only) > 0, k=5)
    assert int(v.sum()) == 1


def test_constant_mask_keyword_postings():
    extra = [{"tag": ["red", "hot"]}, {"tag": "blue"}, {"tag": "red"}, {}, {"tag": "blue"}, {"tag": "green"}]
    _, seg = build_segment(extra=extra)
    fp = seg.postings["tag"]
    block_docs, block_tfs, _ = seg.device("post:tag")
    ids = pad_block_ids(fp.term_block_ids("red"))
    mask = constant_scatter_mask(block_docs, block_tfs, ids, n_docs=seg.n_docs)
    np.testing.assert_array_equal(np.asarray(mask), [True, False, True, False, False, False])
    # multivalued: doc 0 also matches "hot"
    ids = pad_block_ids(fp.term_block_ids("hot"))
    mask = constant_scatter_mask(block_docs, block_tfs, ids, n_docs=seg.n_docs)
    assert bool(mask[0]) and int(np.asarray(mask).sum()) == 1


def test_numeric_column_and_range_mask():
    extra = [{"n": 5}, {"n": [1, 10]}, {"n": 7}, {}, {"n": 3}, {"n": 10}]
    _, seg = build_segment(extra=extra)
    col = seg.numeric["n"]
    np.testing.assert_array_equal(col.range_mask(4, 8, True, True),
                                  [True, False, True, False, False, False])
    # multivalue: doc 1 has values {1,10}; range 9..12 matches it and doc 5
    np.testing.assert_array_equal(col.range_mask(9, 12, True, True),
                                  [False, True, False, False, False, True])


def test_positions_csr():
    _, seg = build_segment()
    fp = seg.postings["body"]
    np.testing.assert_array_equal(fp.positions("the", 0), [0, 6])
    np.testing.assert_array_equal(fp.positions("quick", 1), [0, 1, 2, 5])
    assert len(fp.positions("quick", 3)) == 0


def test_knn_top_k_cosine():
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(6, 8)).astype(np.float32)
    extra = [{"v": vecs[i].tolist()} for i in range(6)]
    _, seg = build_segment(extra=extra)
    import jax.numpy as jnp

    v, norms, exists = seg.device("v:v") if False else seg.device("vec:v")
    q = vecs[2:3]
    top_s, top_o, valid = knn_top_k(jnp.asarray(q), v, norms, exists,
                                    jnp.ones(seg.n_docs, bool), similarity="cosine", k=3)
    assert int(top_o[0, 0]) == 2  # self-similarity wins
    assert float(top_s[0, 0]) == pytest.approx(1.0, abs=2e-2)  # (1+cos)/2, bf16 tolerance
    # parity with numpy
    cos = (vecs @ q[0]) / (np.linalg.norm(vecs, axis=1) * np.linalg.norm(q[0]))
    want_order = np.argsort(-cos)[:3]
    np.testing.assert_array_equal(np.asarray(top_o[0]), want_order)

"""Sandboxed expression scripts: allowlist + contexts."""

import pytest

from elasticsearch_tpu.script import compile_script
from elasticsearch_tpu.script.expressions import ScriptException, doc_map


def test_arithmetic():
    assert compile_script("1 + 2 * 3").execute() == 7
    assert compile_script({"source": "max(a, b) / 2"}).execute({"a": 4, "b": 8}) == 4


def test_painless_isms():
    assert compile_script("a > 1 && b < 2").execute({"a": 2, "b": 1}) is True
    assert compile_script("a != 1 || false").execute({"a": 1}) is False
    assert compile_script("Math.log(1)").execute() == 0.0


def test_doc_access():
    env = {"doc": doc_map({"price": [10.0, 20.0], "empty_f": []})}
    assert compile_script("doc['price'].value * 2").execute(env) == 20.0
    assert compile_script("doc['price'].length").execute(env) == 2
    with pytest.raises(ScriptException):
        compile_script("doc['empty_f'].value").execute(env)


def test_sandbox_rejects():
    for bad in [
        "__import__('os')",
        "().__class__",
        "open('/etc/passwd')",
        "[x for x in (1,)]",
        "lambda: 1",
        "exec('1')",
    ]:
        with pytest.raises(ScriptException):
            compile_script(bad).execute()


def test_runtime_error_wrapped():
    with pytest.raises(ScriptException):
        compile_script("1 / 0").execute()


def test_normalize_preserves_strings_and_identifiers():
    env = {"doc": doc_map({"annulled": [3.0], "status": ["null"]})}
    assert compile_script("doc['annulled'].value").execute(env) == 3.0
    assert compile_script("doc['status'].value == 'null'").execute(env) is True
    assert compile_script("nullable + 1").execute({"nullable": 1}) == 2


def test_compute_limits():
    with pytest.raises(ScriptException):
        compile_script("9**9**7").execute()
    with pytest.raises(ScriptException):
        compile_script("s * 1000000000").execute({"s": "a"})
    assert compile_script("2**10").execute() == 1024


def test_pow_function_is_bounded_like_pow_operator():
    with pytest.raises(ScriptException):
        compile_script("pow(2, 999999999)").execute()
    assert compile_script("pow(2, 10)").execute() == 1024


def test_params_attribute_access():
    assert compile_script("v * params.f").execute(
        {"v": 3, "params": {"f": 2}}) == 6
    assert compile_script("v * params['f']").execute(
        {"v": 3, "params": {"f": 2}}) == 6
    with pytest.raises(ScriptException):
        compile_script("params.missing").execute({"params": {}})

"""TurboBM25 (int8 column cache + Pallas kernels) correctness tests.

Runs on the CPU mesh via pallas interpret mode (tests/conftest.py forces
JAX_PLATFORMS=cpu); differential-checked against a brute-force scorer with
the reference accumulation order.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.segment import build_field_postings
from elasticsearch_tpu.ops import bm25_idf
from elasticsearch_tpu.parallel.spmd import build_stacked_bm25
from elasticsearch_tpu.parallel.turbo import COLD_DF, TurboBM25


class _Seg:
    def __init__(self, n_docs, fp):
        self.n_docs = n_docs
        self.postings = {"body": fp}
        self.vectors = {}


def _corpus(n_docs=3000, vocab=300, seed=0):
    rng = np.random.default_rng(seed)
    probs = 1.0 / np.arange(1, vocab + 1) ** 1.1
    probs /= probs.sum()
    lens = rng.integers(4, 20, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum()), p=probs).astype(np.int64)
    names = [f"t{i}" for i in range(vocab)]
    tok_docs = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    fp = build_field_postings("body", lens, tok_docs, tokens, names)
    return fp, probs, rng


def _agg(q):
    agg = {}
    for t in q:
        agg[t] = agg.get(t, 0.0) + 1.0
    return list(agg.items())


def _brute(fp, avgdl, total_docs, terms, k=10, live=None):
    """Reference scorer: term-at-a-time f32 accumulation in query order."""
    from elasticsearch_tpu.parallel.blockmax import _host_block_scores

    bs = _host_block_scores(fp, avgdl)
    dense = np.zeros(total_docs, np.float32)
    for t, boost in terms:
        o = fp.ord(t)
        if o < 0:
            continue
        w = np.float32(bm25_idf(total_docs, int(fp.doc_freq[o])) * boost)
        lo, hi = int(fp.post_start[o]), int(fp.post_start[o + 1])
        docs = fp.post_doc[lo:hi]
        start, cnt = int(fp.block_start[o]), int(fp.block_count[o])
        vals = bs[start: start + cnt].ravel()[: hi - lo]
        dense[docs] = dense[docs] + w * vals
    if live is not None:
        dense = np.where(live, dense, 0.0)
    docs = np.nonzero(dense > 0)[0]
    sel = np.lexsort((docs, -dense[docs]))[:k]
    return dense[docs[sel]], docs[sel].astype(np.int32)


@pytest.fixture(scope="module")
def engine():
    fp, probs, rng = _corpus()
    stacked = build_stacked_bm25([_Seg(3000, fp)], "body", serve_only=True)
    turbo = TurboBM25(stacked, hbm_budget_bytes=64 << 20)
    return fp, stacked, turbo, probs, rng


def test_cold_only_queries_exact(engine):
    fp, stacked, turbo, probs, rng = engine
    # all terms are cold at this corpus size (df < COLD_DF)
    assert all(int(df) < COLD_DF for df in fp.doc_freq)
    queries = [[f"t{a}", f"t{b}"] for a, b in
               rng.integers(0, 200, size=(16, 2))]
    (scores, ords), = [turbo.search(queries, k=10)]
    for qi, q in enumerate(queries):
        bs, bd = _brute(fp, stacked.avgdl, stacked.total_docs,
                        _agg(q), k=10)
        n = len(bd)
        assert np.array_equal(ords[qi][:n], bd), f"query {qi} docs"
        np.testing.assert_allclose(scores[qi][:n], bs, rtol=1e-6)


def test_colized_path_exact():
    # small dense corpus with COLD_DF forced low so columns engage

    fp, probs, rng = _corpus(n_docs=2000, vocab=50, seed=1)
    stacked = build_stacked_bm25([_Seg(2000, fp)], "body", serve_only=True)
    turbo = TurboBM25(stacked, hbm_budget_bytes=64 << 20, cold_df=10)
    queries = [[f"t{a}", f"t{b}"] for a, b in
               rng.integers(0, 50, size=(12, 2))]
    scores, ords = turbo.search(queries, k=10)
    for qi, q in enumerate(queries):
        bs, bd = _brute(fp, stacked.avgdl, stacked.total_docs,
                        _agg(q), k=10)
        n = len(bd)
        assert np.array_equal(ords[qi][:n], bd), f"query {qi} docs"
        np.testing.assert_allclose(scores[qi][:n], bs, rtol=1e-6)
    assert turbo.stats["builds"] > 0


def test_live_mask_filters_deleted():

    fp, probs, rng = _corpus(n_docs=1500, vocab=40, seed=2)
    live = np.ones(1500, bool)
    live[::3] = False
    stacked = build_stacked_bm25([_Seg(1500, fp)], "body",
                                 live_masks=[live], serve_only=True)
    turbo = TurboBM25(stacked, hbm_budget_bytes=64 << 20, cold_df=10)
    queries = [[f"t{a}", f"t{b}"] for a, b in
               rng.integers(0, 40, size=(6, 2))]
    scores, ords = turbo.search(queries, k=10)
    for qi, q in enumerate(queries):
        bs, bd = _brute(fp, stacked.avgdl, stacked.total_docs,
                        _agg(q),
                        k=10, live=live)
        n = len(bd)
        assert np.array_equal(ords[qi][:n], bd)
        np.testing.assert_allclose(scores[qi][:n], bs, rtol=1e-6)


def test_mixed_and_boosted_queries():

    fp, probs, rng = _corpus(n_docs=2500, vocab=120, seed=3)
    stacked = build_stacked_bm25([_Seg(2500, fp)], "body", serve_only=True)
    # head terms colized, tail cold -> mixed
    turbo = TurboBM25(stacked, hbm_budget_bytes=64 << 20, cold_df=60)
    queries = [[("t0", 2.0), (f"t{100 + i}", 1.0)] for i in range(8)]
    scores, ords = turbo.search(queries, k=10)
    for qi, q in enumerate(queries):
        bs, bd = _brute(fp, stacked.avgdl, stacked.total_docs, q, k=10)
        n = len(bd)
        assert np.array_equal(ords[qi][:n], bd), f"query {qi}"
        np.testing.assert_allclose(scores[qi][:n], bs, rtol=1e-6)


def test_missing_terms_and_empty():
    fp, probs, rng = _corpus(n_docs=1000, vocab=30, seed=4)
    stacked = build_stacked_bm25([_Seg(1000, fp)], "body", serve_only=True)
    turbo = TurboBM25(stacked, hbm_budget_bytes=64 << 20)
    scores, ords = turbo.search([["zzz_missing"], ["t0", "zzz_missing"]],
                                k=5)
    assert float(scores[0].sum()) == 0.0
    bs, bd = _brute(fp, stacked.avgdl, stacked.total_docs,
                    [("t0", 1.0)], k=5)
    assert np.array_equal(ords[1][: len(bd)], bd)


def test_capacity_overflow_degrades_to_cold():
    """A batch whose colizable terms exceed cache capacity must degrade
    gracefully (ADVICE r4): overflow terms score host-exact, results stay
    identical to brute force."""
    fp, probs, rng = _corpus(n_docs=3000, vocab=80, seed=7)
    stacked = build_stacked_bm25([_Seg(3000, fp)], "body", serve_only=True)
    # hbm budget floor is 32 slots; make nearly every term colizable so one
    # batch demands more columns than capacity
    turbo = TurboBM25(stacked, hbm_budget_bytes=1, cold_df=5)
    assert turbo.Hp == 32
    queries = [[f"t{i}", f"t{(i + 37) % 80}"] for i in range(40)]
    scores, ords = turbo.search(queries, k=10)
    assert turbo.stats["degraded"] > 0
    for qi, q in enumerate(queries):
        bs, bd = _brute(fp, stacked.avgdl, stacked.total_docs, _agg(q), k=10)
        n = len(bd)
        assert np.array_equal(ords[qi][:n], bd), f"query {qi}"
        np.testing.assert_allclose(scores[qi][:n], bs, rtol=1e-6)


def test_qc_sizes_rounded_and_intermediate_used():
    fp, probs, rng = _corpus(n_docs=1200, vocab=30, seed=8)
    stacked = build_stacked_bm25([_Seg(1200, fp)], "body", serve_only=True)
    turbo = TurboBM25(stacked, hbm_budget_bytes=64 << 20, cold_df=10,
                      qc_sizes=(3, 20, 64))
    # rounded up to ROWS_PER_STEP multiples, deduped, ascending
    assert turbo.qc_sizes == (8, 24, 64)
    queries = [[f"t{i % 30}", f"t{(i + 11) % 30}"] for i in range(17)]
    scores, ords = turbo.search(queries, k=5)   # 17 -> qc 24 (intermediate)
    for qi, q in enumerate(queries):
        bs, bd = _brute(fp, stacked.avgdl, stacked.total_docs, _agg(q), k=5)
        n = len(bd)
        assert np.array_equal(ords[qi][:n], bd), f"query {qi}"

"""Cross-cluster search & replication suite (PR 20).

Two in-process clusters over independent `LocalNodeChannels`, joined by
a `RemoteClusterService` registry on the querying side. Pins:

  * CCS fan-out for `remote:index` patterns merges BIT-identically to
    the local multi-index merge (the acceptance bar: a healthy fan-out
    and a local merged search over the same data agree hit-for-hit).
  * partial-results semantics: a dead `skip_unavailable=true` remote
    degrades to a `_clusters.skipped` entry — never a 5xx; without the
    flag the transport error propagates.
  * `#cluster` fault selectors: `rpc_remote_search#<alias>:raise` burns
    attempts against the retry budget, `rpc_ccr_fetch#<alias>:hang`
    surfaces as RpcTimeoutError under the ES_TPU_RPC_TIMEOUT_MS floor
    and the next poll recovers.
  * CCR: follow -> converge -> pause -> resume, seq-no idempotent
    re-apply, checksum-mismatch bounded re-fetch, follower stats lag
    accounting.
  * REST: /_remote/info, /{index}/_ccr/*, `tpu_ccs`/`tpu_ccr` stats
    sections, and the msearch line that targets only dead
    skip_unavailable remotes coming back empty-but-well-formed.
"""

import json

import pytest

from elasticsearch_tpu.cluster.remote import (
    RemoteClusterService, merge_leg_responses,
)
from elasticsearch_tpu.cluster_node import form_local_cluster
from elasticsearch_tpu.common import faults, metrics
from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.common.faults import inject
from elasticsearch_tpu.common.integrity import SegmentCorruptedError
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.parallel.routing import shard_for_id
from elasticsearch_tpu.rest import RestController, register_handlers
from elasticsearch_tpu.transport.channels import (
    LocalNodeChannels, NodeUnavailableError,
)

pytestmark = pytest.mark.distributed


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture()
def two_clusters(tmp_path):
    """A 'follower' 2-node cluster with a 'leader' 3-node cluster
    registered as remote alias `leader` (skip_unavailable=True)."""
    L_nodes, L_store, L_ch = form_local_cluster(
        ["L-m0", "L-d0", "L-d1"], str(tmp_path / "L"))
    F_nodes, F_store, F_ch = form_local_cluster(
        ["F-m0", "F-d0"], str(tmp_path / "F"))
    for n in F_nodes:
        n.remotes.register_remote("leader", L_ch, ["L-d0", "L-d1"],
                                  skip_unavailable=True)
    yield L_nodes, L_ch, F_nodes, F_ch
    for n in L_nodes + F_nodes:
        n.close()


def _seed_leader(L, index="logs", n=20, shards=2, replicas=1):
    L[0].create_index(index, {"settings": {
        "index.number_of_shards": shards,
        "index.number_of_replicas": replicas}})
    for i in range(n):
        L[0].index_doc(index, f"d{i}", {"n": i, "body": f"doc {i}"})
    L[0].refresh(index)


def _seed_local(F, index="local", n=5):
    F[0].create_index(index, {"settings": {
        "index.number_of_shards": 1, "index.number_of_replicas": 0}})
    for i in range(n):
        F[0].index_doc(index, f"l{i}", {"n": 100 + i, "body": f"loc {i}"})
    F[0].refresh(index)


def _read(nodes, index, doc_id):
    """Realtime get through the current primary's engine (the chaos
    harness's authoritative-read idiom)."""
    state = nodes[0].state
    sid = shard_for_id(doc_id, state.indices[index].number_of_shards)
    r = state.primary_of(index, sid)
    owner = next(n for n in nodes if n.node_name == r.node_id)
    hit = owner.shard_service.get_shard(index, sid).engine.get(doc_id)
    return None if hit is None else hit["_source"]


# ------------------------------------------------------------ registry


def test_split_expression_and_unknown_alias():
    svc = RemoteClusterService("n0")
    svc.register_remote("east", LocalNodeChannels(), ["a"])
    local, remote = svc.split_expression("idx1,east:logs-*,idx2,east:more")
    assert local == ["idx1", "idx2"]
    assert remote == {"east": ["logs-*", "more"]}
    with pytest.raises(IllegalArgumentError):
        svc.split_expression("typo:logs")
    with pytest.raises(IllegalArgumentError):
        svc.register_remote("bad:name", LocalNodeChannels(), ["a"])
    with pytest.raises(IllegalArgumentError):
        svc.register_remote("noseeds", LocalNodeChannels(), [])
    assert not svc.has_remote_parts("idx1,idx2")
    assert svc.has_remote_parts("east:logs")


# ------------------------------------------------------------ CCS


def test_ccs_fanout_bit_identical_to_local_merge(two_clusters):
    """A healthy `local,leader:logs` fan-out must agree hit-for-hit with
    the same data merged locally: mirror the leader index into the
    follower cluster and compare (only `_index` carries the alias)."""
    L, _, F, _ = two_clusters
    _seed_leader(L, "logs", n=20)
    _seed_local(F, "local", n=5)
    # mirror of the leader data inside the follower cluster
    F[0].create_index("logs_mirror", {"settings": {
        "index.number_of_shards": 2, "index.number_of_replicas": 0}})
    for i in range(20):
        F[0].index_doc("logs_mirror", f"d{i}", {"n": i, "body": f"doc {i}"})
    F[0].refresh("logs_mirror")

    body = {"query": {"match": {"body": "doc"}}, "size": 30, "from": 0}
    ccs = F[0].search("local,leader:logs", dict(body))
    loc = F[0].search("local,logs_mirror", dict(body))

    assert ccs["_clusters"] == {
        "total": 2, "successful": 2, "skipped": 0, "partial": 0,
        "details": ccs["_clusters"]["details"]}
    assert ccs["hits"]["total"]["value"] == loc["hits"]["total"]["value"]

    def normalize(hits):
        return [(h["_id"], h.get("_score"), h.get("sort"))
                for h in hits]

    assert normalize(ccs["hits"]["hits"]) == normalize(loc["hits"]["hits"])
    # remote hits carry the cluster-qualified index name
    remote_hits = [h for h in ccs["hits"]["hits"]
                   if h["_index"].startswith("leader:")]
    assert len(remote_hits) == 20


def test_ccs_sorted_fanout_agreement(two_clusters):
    L, _, F, _ = two_clusters
    _seed_leader(L, "logs", n=12)
    _seed_local(F, "local", n=6)
    body = {"query": {"match_all": {}}, "size": 10,
            "sort": [{"n": {"order": "desc"}}]}
    r = F[0].search("local,leader:logs", dict(body))
    ns = [h["_source"]["n"] for h in r["hits"]["hits"]]
    assert ns == sorted(ns, reverse=True)
    assert ns[:6] == [105, 104, 103, 102, 101, 100]


def test_ccs_aggs_rejected(two_clusters):
    L, _, F, _ = two_clusters
    _seed_leader(L, "logs", n=3)
    with pytest.raises(IllegalArgumentError):
        F[0].search("leader:logs", {"aggs": {
            "m": {"max": {"field": "n"}}}})


def test_ccs_skip_unavailable_dead_remote_degrades_to_skipped(two_clusters):
    L, L_ch, F, _ = two_clusters
    _seed_leader(L, "logs", n=8)
    _seed_local(F, "local", n=4)
    for name in ("L-d0", "L-d1"):
        L_ch.kill(name)
    r = F[0].search("local,leader:logs", {"query": {"match_all": {}},
                                          "size": 20})
    assert r["hits"]["total"]["value"] == 4     # local leg only
    c = r["_clusters"]
    assert (c["total"], c["successful"], c["skipped"]) == (2, 1, 1)
    assert c["details"]["leader"]["status"] == "skipped"
    # the skipped-cluster counter feeds the tpu_ccs stats section
    assert F[0].remotes.stats()["skipped_clusters"] >= 1


def test_ccs_dead_remote_without_skip_unavailable_raises(two_clusters):
    L, L_ch, F, _ = two_clusters
    _seed_leader(L, "logs", n=4)
    for n in F:
        n.remotes.register_remote("strict", L_ch, ["L-d0"],
                                  skip_unavailable=False)
    L_ch.kill("L-d0")
    L_ch.kill("L-d1")
    with pytest.raises(NodeUnavailableError):
        F[0].search("strict:logs", {"query": {"match_all": {}}})


def test_ccs_fault_selector_per_cluster_with_retry(two_clusters,
                                                   monkeypatch):
    """`rpc_remote_search#leader:raisex1` kills the first attempt only;
    the budgeted retry (ES_TPU_REMOTE_RETRIES=1 default) rotates to the
    next seed and the fan-out still succeeds."""
    L, _, F, _ = two_clusters
    _seed_leader(L, "logs", n=6)
    monkeypatch.setenv("ES_TPU_REMOTE_BACKOFF_MS", "0")
    before = metrics.counter_values()["ccs_remote_retries"]
    with inject("rpc_remote_search#leader:raisex1"):
        r = F[0].search("leader:logs", {"query": {"match_all": {}},
                                        "size": 10})
    assert r["hits"]["total"]["value"] == 6
    assert r["_clusters"]["successful"] == 1
    assert metrics.counter_values()["ccs_remote_retries"] == before + 1


def test_ccs_fault_exhausted_budget_skips(two_clusters, monkeypatch):
    """Every attempt dies -> a skip_unavailable remote degrades to
    skipped, never an error response."""
    L, _, F, _ = two_clusters
    _seed_leader(L, "logs", n=6)
    _seed_local(F, "local", n=2)
    monkeypatch.setenv("ES_TPU_REMOTE_BACKOFF_MS", "0")
    with inject("rpc_remote_search#leader:raisexinf"):
        r = F[0].search("local,leader:logs",
                        {"query": {"match_all": {}}, "size": 20})
    assert r["hits"]["total"]["value"] == 2
    assert r["_clusters"]["skipped"] == 1


# ------------------------------------------------------------ CCR


def test_ccr_follow_converges_and_stays_idempotent(two_clusters,
                                                   monkeypatch):
    L, _, F, _ = two_clusters
    monkeypatch.setenv("ES_TPU_CCR_POLL_MS", "0")
    _seed_leader(L, "logs", n=15)
    r = F[0].ccr.follow("logs_copy", "leader", "logs")
    assert r["index_following_started"]
    assert F[0].ccr.poll_once() == 15
    F[0].refresh("logs_copy")
    got = F[0].search("logs_copy", {"query": {"match_all": {}},
                                    "size": 50})
    assert got["hits"]["total"]["value"] == 15
    # idempotent: a second poll ships nothing
    assert F[0].ccr.poll_once() == 0
    # incremental: updates + deletes converge too
    L[0].index_doc("logs", "d0", {"n": 999, "body": "updated"})
    L[0].bulk("logs", [{"op": "delete", "id": "d1"}])
    L[0].index_doc("logs", "d99", {"n": 99, "body": "fresh"})
    assert F[0].ccr.poll_once() > 0
    F[0].refresh("logs_copy")
    got = F[0].search("logs_copy", {"query": {"match_all": {}},
                                    "size": 50})
    assert got["hits"]["total"]["value"] == 15  # -1 delete +1 fresh
    assert _read(F, "logs_copy", "d0")["n"] == 999
    # per-shard lag accounting is zero after convergence
    st = F[0].ccr.follower_stats("logs_copy")["indices"][0]
    assert all(s["lag_ops"] == 0 for s in st["shards"])


def test_ccr_pause_resume(two_clusters, monkeypatch):
    L, _, F, _ = two_clusters
    monkeypatch.setenv("ES_TPU_CCR_POLL_MS", "0")
    _seed_leader(L, "logs", n=5)
    F[0].ccr.follow("logs_copy", "leader", "logs")
    F[0].ccr.poll_once()
    F[0].ccr.pause_follow("logs_copy")
    L[0].index_doc("logs", "late", {"n": 1000, "body": "late"})
    assert F[0].ccr.poll_once() == 0        # paused: nothing moves
    F[0].ccr.resume_follow("logs_copy")
    assert F[0].ccr.poll_once() >= 1
    F[0].refresh("logs_copy")
    assert _read(F, "logs_copy", "late")["n"] == 1000


def test_ccr_fetch_hang_times_out_then_recovers(two_clusters,
                                                monkeypatch):
    """`rpc_ccr_fetch#leader:hang` under a 50ms RPC floor surfaces as a
    timeout; the in-request budgeted retry recovers, counting
    ccr_fetch_retries."""
    L, _, F, _ = two_clusters
    monkeypatch.setenv("ES_TPU_CCR_POLL_MS", "0")
    monkeypatch.setenv("ES_TPU_RPC_TIMEOUT_MS", "50")
    monkeypatch.setenv("ES_TPU_REMOTE_BACKOFF_MS", "0")
    _seed_leader(L, "logs", n=8, replicas=0)
    F[0].ccr.follow("logs_copy", "leader", "logs")
    before = metrics.counter_values()["ccr_fetch_retries"]
    with inject("rpc_ccr_fetch#leader:hangx1=0.2"):
        applied = F[0].ccr.poll_once()
    assert applied == 8
    assert metrics.counter_values()["ccr_fetch_retries"] > before
    F[0].refresh("logs_copy")
    got = F[0].search("logs_copy", {"query": {"match_all": {}},
                                    "size": 20})
    assert got["hits"]["total"]["value"] == 8


def test_ccr_leader_down_poll_survives_then_catches_up(two_clusters,
                                                       monkeypatch):
    L, L_ch, F, _ = two_clusters
    monkeypatch.setenv("ES_TPU_CCR_POLL_MS", "0")
    monkeypatch.setenv("ES_TPU_REMOTE_BACKOFF_MS", "0")
    _seed_leader(L, "logs", n=6)
    F[0].ccr.follow("logs_copy", "leader", "logs")
    F[0].ccr.poll_once()
    for name in ("L-d0", "L-d1"):
        L_ch.kill(name)
    # leader gone: the poll records the error and returns, no raise
    assert F[0].ccr.poll_once() == 0
    st = F[0].ccr.follower_stats("logs_copy")["indices"][0]
    assert "last_error" in st
    for name in ("L-d0", "L-d1"):
        L_ch.revive(name)
    L[0].index_doc("logs", "post", {"n": 7, "body": "post-heal"})
    assert F[0].ccr.poll_once() >= 1
    F[0].refresh("logs_copy")
    assert _read(F, "logs_copy", "post")["n"] == 7


def test_ccr_checksum_mismatch_bounded_refetch(two_clusters,
                                               monkeypatch):
    """Wire corruption (`segment_transfer#leader`, fired follower-side
    on a COPY of the batch) fails sha256 verification and re-fetches,
    bounded by ES_TPU_REMOTE_RETRIES; persistent rot raises
    SegmentCorruptedError without poisoning the follower."""
    L, _, F, _ = two_clusters
    monkeypatch.setenv("ES_TPU_CCR_POLL_MS", "0")
    _seed_leader(L, "logs", n=10, shards=1, replicas=0)
    F[0].ccr.follow("logs_copy", "leader", "logs")
    before = metrics.counter_values()["ccr_checksum_mismatches"]
    # one corrupted transfer, then clean: the bounded re-fetch recovers
    with inject("segment_transfer#leader:raisex1"):
        assert F[0].ccr.poll_once() == 10
    assert metrics.counter_values()["ccr_checksum_mismatches"] == before + 1
    F[0].refresh("logs_copy")
    got = F[0].search("logs_copy", {"query": {"match_all": {}},
                                    "size": 20})
    assert got["hits"]["total"]["value"] == 10
    # persistent rot: every fetch+retry corrupted -> bounded error;
    # nothing half-applied on the follower
    L[0].index_doc("logs", "rot", {"n": -1, "body": "rot"})
    with inject("segment_transfer#leader:raisexinf"):
        assert F[0].ccr.poll_once() == 0
    st = F[0].ccr.follower_stats("logs_copy")["indices"][0]
    assert "SegmentCorruptedError" in st.get("last_error", "")
    assert _read(F, "logs_copy", "rot") is None
    # heal: the same ops land on the next clean poll
    assert F[0].ccr.poll_once() == 1


def test_ccr_follow_unknown_remote_or_index(two_clusters):
    L, _, F, _ = two_clusters
    _seed_leader(L, "logs", n=2)
    with pytest.raises(IllegalArgumentError):
        F[0].ccr.follow("x", "nope", "logs")
    from elasticsearch_tpu.common.errors import IndexNotFoundError

    with pytest.raises(IndexNotFoundError):
        F[0].ccr.follow("x", "leader", "missing")
    with pytest.raises(IndexNotFoundError):
        F[0].ccr.pause_follow("never_followed")


# ------------------------------------------------------------ stats / info


def test_remote_info_probes_liveness(two_clusters):
    L, L_ch, F, _ = two_clusters
    info = F[0].remotes.remote_info()
    assert info["leader"]["connected"]
    assert info["leader"]["num_nodes_connected"] == 2
    assert info["leader"]["skip_unavailable"] is True
    L_ch.kill("L-d0")
    L_ch.kill("L-d1")
    info = F[0].remotes.remote_info()
    assert not info["leader"]["connected"]
    assert info["leader"]["num_nodes_connected"] == 0


def test_tpu_ccs_stats_edges_and_circuits(two_clusters, monkeypatch):
    L, L_ch, F, _ = two_clusters
    _seed_leader(L, "logs", n=3)
    monkeypatch.setenv("ES_TPU_REMOTE_BACKOFF_MS", "0")
    F[0].search("leader:logs", {"query": {"match_all": {}}})
    st = F[0].remotes.stats()
    assert st["remote_clusters"] == ["leader"]
    assert st["remote_searches"] >= 1
    assert any(e["name"].startswith("leader:") for e in st["edges"])


# ------------------------------------------------------------ REST layer


@pytest.fixture()
def rest_pair(tmp_path):
    """A standalone REST node with a second standalone node registered
    as remote `east` over a private LocalNodeChannels."""
    local = Node(node_name="rest-local")
    east = Node(node_name="east-0")
    ch = LocalNodeChannels()
    ch.register("east-0", east.transport)
    local.remotes.register_remote("east", ch, ["east-0"],
                                  skip_unavailable=True)
    rc = RestController()
    register_handlers(local, rc)

    def call(method, path, body=None, params=None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body)
        return rc.dispatch(method, path, params or {}, body)

    yield call, local, east, ch
    local.close()
    east.close()


def test_rest_ccs_search_and_remote_info(rest_pair):
    call, local, east, ch = rest_pair
    east.create_index("logs", {"settings": {"number_of_shards": 1}})
    east.indices.get("logs").index_doc("e1", {"n": 1, "body": "hello"})
    east.indices.get("logs").refresh()
    call("PUT", "/home", {"settings": {"number_of_shards": 1}})
    call("PUT", "/home/_doc/h1", {"n": 2, "body": "hello"})
    call("POST", "/home/_refresh")
    r = call("POST", "/home,east:logs/_search",
             {"query": {"match": {"body": "hello"}}, "size": 10})
    assert r.status == 200
    assert r.body["hits"]["total"]["value"] == 2
    assert r.body["_clusters"]["successful"] == 2
    assert {h["_index"] for h in r.body["hits"]["hits"]} \
        == {"home", "east:logs"}
    info = call("GET", "/_remote/info")
    assert info.status == 200 and info.body["east"]["connected"]


def test_rest_msearch_dead_remote_line_well_formed(rest_pair):
    """The satellite fix: an msearch line whose expression targets only
    dead skip_unavailable remotes returns an EMPTY well-formed response
    with `_clusters.skipped` counted — not a shard-failure/error entry,
    and it must not poison sibling lines."""
    call, local, east, ch = rest_pair
    call("PUT", "/home", {"settings": {"number_of_shards": 1}})
    call("PUT", "/home/_doc/h1", {"n": 2, "body": "hi"})
    call("POST", "/home/_refresh")
    ch.kill("east-0")
    payload = (json.dumps({"index": "east:logs"}) + "\n"
               + json.dumps({"query": {"match_all": {}}}) + "\n"
               + json.dumps({"index": "home"}) + "\n"
               + json.dumps({"query": {"match_all": {}}}) + "\n")
    r = call("POST", "/_msearch", payload)
    assert r.status == 200
    dead, alive = r.body["responses"]
    assert "error" not in dead
    assert dead["status"] == 200
    assert dead["hits"]["total"]["value"] == 0
    assert dead["hits"]["hits"] == []
    assert dead["_clusters"]["skipped"] == 1
    assert alive["hits"]["total"]["value"] == 1


def test_rest_ccr_endpoints_and_stats_sections(rest_pair, monkeypatch):
    call, local, east, ch = rest_pair
    monkeypatch.setenv("ES_TPU_CCR_POLL_MS", "0")
    east.create_index("logs", {"settings": {"number_of_shards": 1}})
    for i in range(4):
        east.indices.get("logs").index_doc(f"e{i}", {"n": i})
    r = call("PUT", "/logs_copy/_ccr/follow",
             {"remote_cluster": "east", "leader_index": "logs"})
    assert r.status == 200 and r.body["index_following_started"]
    assert call("PUT", "/nocluster/_ccr/follow",
                {"leader_index": "logs"}).status == 400
    local.ccr.poll_once()
    r = call("GET", "/logs_copy/_ccr/stats")
    assert r.status == 200
    shard = r.body["indices"][0]["shards"][0]
    assert shard["follower_checkpoint"] == 3 and shard["lag_ops"] == 0
    assert call("POST", "/logs_copy/_ccr/pause_follow").body["acknowledged"]
    assert call("POST", "/logs_copy/_ccr/resume_follow").body["acknowledged"]
    stats = call("GET", "/_nodes/stats")
    node_stats = next(iter(stats.body["nodes"].values()))
    assert "tpu_ccs" in node_stats and "tpu_ccr" in node_stats
    assert node_stats["tpu_ccr"]["followers"][0]["index"] == "logs_copy"
    assert node_stats["tpu_ccs"]["remote_clusters"] == ["east"]


# ------------------------------------------------------------ merge unit


def test_merge_leg_responses_prefixes_and_slices():
    def leg(idx, scores):
        return {"took": 1, "timed_out": False,
                "_shards": {"total": 1, "successful": 1, "skipped": 0,
                            "failed": 0},
                "hits": {"total": {"value": len(scores), "relation": "eq"},
                         "max_score": max(scores),
                         "hits": [{"_index": idx, "_id": f"{idx}{i}",
                                   "_score": s}
                                  for i, s in enumerate(scores)]}}

    merged = merge_leg_responses(
        [(None, leg("a", [3.0, 1.0])), ("r", leg("b", [2.0]))],
        from_=0, size=2)
    assert [h["_id"] for h in merged["hits"]["hits"]] == ["a0", "b0"]
    assert merged["hits"]["hits"][1]["_index"] == "r:b"
    assert merged["hits"]["total"]["value"] == 3
    # pagination slices AFTER the global merge
    page2 = merge_leg_responses(
        [(None, leg("a", [3.0, 1.0])), ("r", leg("b", [2.0]))],
        from_=2, size=2)
    assert [h["_id"] for h in page2["hits"]["hits"]] == ["a1"]

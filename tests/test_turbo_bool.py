"""TurboBM25 conjunctive + slop-0 phrase differential suite.

Three routes through the SAME engine must agree bit-for-bit, because all
of them rescore through _exact_bool (f64 accumulation in spec clause
order, one f32 downcast):

  * device: presence-mask sweep over resident int8 columns (Pallas
    kernels in interpret mode on the CPU mesh — tests/conftest.py forces
    JAX_PLATFORMS=cpu),
  * forced certificate failure: device collection discarded, exact host
    fallback (turbo.force_cert_fail test hook),
  * all-cold: a fresh engine with cold_df above every df, so every query
    takes the host sparse-intersection path with no columns at all.

Ground truth is an independent numpy scorer (tf lookups shared, formula
and phrase-position walk reimplemented here).
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.segment import build_field_postings, tf_at
from elasticsearch_tpu.ops import bm25_idf
from elasticsearch_tpu.parallel.spmd import build_stacked_bm25
from elasticsearch_tpu.parallel.turbo import TurboBM25

K1, B = 1.2, 0.75


class _Seg:
    def __init__(self, n_docs, fp):
        self.n_docs = n_docs
        self.postings = {"body": fp}
        self.vectors = {}


def _pcorpus(n_docs=2000, vocab=60, seed=11):
    """Positional Zipf corpus: token_pos is the in-doc offset, so every
    adjacent token pair is a real slop-0 phrase occurrence."""
    rng = np.random.default_rng(seed)
    probs = 1.0 / np.arange(1, vocab + 1) ** 1.1
    probs /= probs.sum()
    lens = rng.integers(4, 24, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum()), p=probs).astype(np.int64)
    tok_docs = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    bounds = np.concatenate([[0], np.cumsum(lens)])
    tok_pos = (np.arange(len(tokens), dtype=np.int64)
               - np.repeat(bounds[:-1], lens))
    names = [f"t{i}" for i in range(vocab)]
    fp = build_field_postings("body", lens, tok_docs, tokens, names,
                              token_pos=tok_pos)
    return fp, lens, tokens, bounds, rng


def _engine(fp, n_docs, live=None, cold_df=5, hbm=64 << 20):
    stacked = build_stacked_bm25(
        [_Seg(n_docs, fp)], "body",
        live_masks=None if live is None else [live], serve_only=True)
    return TurboBM25(stacked, hbm_budget_bytes=hbm, cold_df=cold_df), stacked


def _phrase_pf_brute(fp, terms, doc):
    """Slop-0 phrase frequency by direct position walk."""
    pos = [set(fp.positions(t, doc).tolist()) for t in terms]
    return sum(1 for p0 in pos[0]
               if all(p0 + i in pos[i] for i in range(1, len(terms))))


def _brute_bool(fp, avgdl, total_docs, spec, k=10, live=None):
    """Independent reference: same clause order / f64 accumulation as
    _exact_bool, tf via postings lookup, phrase freq via position walk."""
    n = fp.doc_len.shape[0] if hasattr(fp.doc_len, "shape") else len(fp.doc_len)
    docs = np.arange(n, dtype=np.int64)
    dl = np.asarray(fp.doc_len)[docs]
    norm = K1 * (1.0 - B + B * dl / max(avgdl, 1e-9))
    scores = np.zeros(n, np.float64)
    match = np.ones(n, bool)
    for t, w in spec.get("must", ()):
        if fp.ord(t) < 0:
            return []
        idf = bm25_idf(total_docs, int(fp.doc_freq[fp.ord(t)]))
        tf, present = tf_at(fp, t, docs)
        match &= present
        scores += w * idf * tf * (K1 + 1.0) / (tf + norm)
    for t in spec.get("filter", ()):
        if fp.ord(t) < 0:
            return []
        _, present = tf_at(fp, t, docs)
        match &= present
    for t, w in spec.get("should", ()):
        if fp.ord(t) < 0:
            continue
        idf = bm25_idf(total_docs, int(fp.doc_freq[fp.ord(t)]))
        tf, present = tf_at(fp, t, docs)
        contrib = w * idf * tf * (K1 + 1.0) / np.maximum(tf + norm, 1e-9)
        scores += np.where(present, contrib, 0.0)
    for terms, slop, boost in spec.get("phrases", ()):
        assert slop == 0, "brute reference is slop-0 only"
        if any(fp.ord(t) < 0 for t in terms):
            return []
        idf_sum = sum(bm25_idf(total_docs, int(fp.doc_freq[fp.ord(t)]))
                      for t in terms)
        pf = np.zeros(n, np.float64)
        cand = match.nonzero()[0] if spec.get("must") or spec.get("filter") \
            else docs
        for d in cand:
            pf[d] = _phrase_pf_brute(fp, terms, int(d))
        match &= pf > 0
        if boost != 0.0:
            scores += boost * idf_sum * pf * (K1 + 1.0) / (pf + norm)
    for t in spec.get("must_not", ()):
        if fp.ord(t) < 0:
            continue
        _, present = tf_at(fp, t, docs)
        match &= ~present
    if live is not None:
        match &= live
    keep = match & (scores > 0)
    sel = docs[keep]
    s32 = scores[keep].astype(np.float32)
    order = np.lexsort((sel, -s32))[:k]
    return [(float(s32[j]), int(sel[j])) for j in order]


def _draw_specs(rng, vocab, n=24, bounds=None, tokens=None):
    """Mixed bool specs across all clause kinds; when the corpus arrays
    are given, half the phrase draws come from real adjacent pairs."""
    specs = []
    for i in range(n):
        t = rng.choice(vocab, size=6, replace=False)
        spec = {}
        if i % 3 != 2:
            spec["must"] = [(f"t{t[0]}", 1.0)]
            if i % 2:
                spec["must"].append((f"t{t[1]}", float(rng.choice([1.0, 2.0]))))
        spec["should"] = [(f"t{t[2]}", 1.0), (f"t{t[3]}", 0.5)]
        if i % 4 == 0:
            spec["filter"] = [f"t{t[4]}"]
        if i % 5 == 0:
            spec["must_not"] = [f"t{t[5]}"]
        if i % 3 == 2 and bounds is not None:
            d = int(rng.integers(0, len(bounds) - 1))
            lo, hi = int(bounds[d]), int(bounds[d + 1])
            j = int(rng.integers(lo, hi - 1))
            a, b = int(tokens[j]), int(tokens[j + 1])
            if a != b:
                spec["phrases"] = [([f"t{a}", f"t{b}"], 0, 1.0)]
        specs.append(spec)
    # hot-term and absent-term edges
    specs.append({"must": [("t0", 1.0), ("t1", 1.0)], "filter": ["t2"]})
    specs.append({"must": [("t0", 1.0)], "must_not": ["t1"]})
    specs.append({"must": [("absent", 1.0), ("t1", 1.0)]})
    specs.append({"should": [("t3", 1.0), ("t7", 2.0)]})
    return specs


@pytest.fixture(scope="module")
def corpus():
    return _pcorpus()


def _run_routes(fp, n_docs, specs, live=None, k=10):
    """(device, cert-fail fallback, all-cold host) result triples."""
    dev, _ = _engine(fp, n_docs, live=live, cold_df=5)
    got_dev = dev.search_bool(specs, k=k)
    dev.force_cert_fail = True
    got_fb = dev.search_bool(specs, k=k)
    cold, _ = _engine(fp, n_docs, live=live, cold_df=1 << 30)
    got_cold = cold.search_bool(specs, k=k)
    assert dev.stats["bool_device"] > 0, "device route never engaged"
    assert cold.stats["bool_host"] > 0, "host route never engaged"
    return got_dev, got_fb, got_cold, dev, cold


def _assert_identical(a, b, label):
    (sa, da), (sb, db) = a, b
    assert np.array_equal(da, db), f"{label}: doc ids differ"
    assert np.array_equal(sa, sb), f"{label}: scores differ (not bit-identical)"


def test_bool_routes_bit_identical(corpus):
    fp, lens, tokens, bounds, rng = corpus
    specs = _draw_specs(rng, 60, bounds=bounds, tokens=tokens)
    got_dev, got_fb, got_cold, *_ = _run_routes(fp, len(lens), specs)
    _assert_identical(got_dev, got_fb, "device vs cert-fail fallback")
    _assert_identical(got_dev, got_cold, "device vs all-cold host")


def test_bool_matches_brute_force(corpus):
    fp, lens, tokens, bounds, rng = corpus
    specs = _draw_specs(rng, 60, n=16, bounds=bounds, tokens=tokens)
    turbo, stacked = _engine(fp, len(lens), cold_df=5)
    scores, ords = turbo.search_bool(specs, k=10)
    for qi, spec in enumerate(specs):
        want = _brute_bool(fp, stacked.avgdl, stacked.total_docs, spec, 10)
        got = [(float(scores[qi][j]), int(ords[qi][j]))
               for j in range(10) if scores[qi][j] > 0]
        assert len(got) == len(want), f"query {qi}: {spec}"
        for (es, eo), (gs, go) in zip(want, got):
            assert abs(es - gs) <= 1e-6 * abs(es) + 1e-7, f"query {qi}"
        ws = np.asarray([w[0] for w in want])
        gaps = np.abs(np.diff(ws)) > 1e-6 * np.abs(ws[:-1]) + 1e-7
        if gaps.all():
            assert [o for _, o in want] == [o for _, o in got], f"query {qi}"


def test_phrase_slop0_routes_bit_identical(corpus):
    fp, lens, tokens, bounds, rng = corpus
    phrases = []
    while len(phrases) < 12:
        d = int(rng.integers(0, len(lens)))
        lo, hi = int(bounds[d]), int(bounds[d + 1])
        j = int(rng.integers(lo, hi - 1))
        a, b = int(tokens[j]), int(tokens[j + 1])
        if a != b:
            phrases.append([f"t{a}", f"t{b}"])
    dev, _ = _engine(fp, len(lens), cold_df=5)
    s1, d1 = dev.search_phrase(phrases, k=10, slop=0)
    assert dev.stats["phrase_builds"] > 0, "adjacency columns never built"
    dev.force_cert_fail = True
    s2, d2 = dev.search_phrase(phrases, k=10, slop=0)
    cold, _ = _engine(fp, len(lens), cold_df=1 << 30)
    s3, d3 = cold.search_phrase(phrases, k=10, slop=0)
    _assert_identical((s1, d1), (s2, d2), "phrase device vs cert-fail")
    _assert_identical((s1, d1), (s3, d3), "phrase device vs all-cold")
    # each phrase was drawn from a real adjacency: it must match something
    assert (s1[:, 0] > 0).all()
    # ... and agree with the position-walk brute force
    stacked = build_stacked_bm25([_Seg(len(lens), fp)], "body",
                                 serve_only=True)
    for qi, p in enumerate(phrases[:4]):
        want = _brute_bool(fp, stacked.avgdl, stacked.total_docs,
                           {"phrases": [(p, 0, 1.0)]}, 10)
        got = [(float(s1[qi][j]), int(d1[qi][j]))
               for j in range(10) if s1[qi][j] > 0]
        assert [o for _, o in want] == [o for _, o in got], f"phrase {qi}"


def test_deleted_docs_excluded_on_all_routes(corpus):
    fp, lens, tokens, bounds, rng = corpus
    live = np.ones(len(lens), bool)
    live[::3] = False
    specs = _draw_specs(rng, 60, n=10, bounds=bounds, tokens=tokens)
    got_dev, got_fb, got_cold, *_ = _run_routes(fp, len(lens), specs,
                                                live=live)
    _assert_identical(got_dev, got_fb, "deleted: device vs cert-fail")
    _assert_identical(got_dev, got_cold, "deleted: device vs all-cold")
    scores, ords = got_dev
    hit = ords[scores > 0]
    assert live[hit].all(), "a deleted doc surfaced in the top-k"


def test_capacity_degradation_stays_exact(corpus):
    """Columns + phrases far beyond the slot budget: the engine degrades
    to host scoring for the overflow, twice in a row (the second call
    used to crash ensure_phrases on an empty build dispatch), and stays
    bit-identical to the uncached route throughout."""
    fp, lens, tokens, bounds, rng = corpus
    turbo, _ = _engine(fp, len(lens), cold_df=5, hbm=256 << 10)
    assert turbo.Hp < 40, "budget too generous for a degradation test"
    phrases = []
    while len(phrases) < 48:
        d = int(rng.integers(0, len(lens)))
        lo, hi = int(bounds[d]), int(bounds[d + 1])
        j = int(rng.integers(lo, hi - 1))
        a, b = int(tokens[j]), int(tokens[j + 1])
        if a != b and [f"t{a}", f"t{b}"] not in phrases:
            phrases.append([f"t{a}", f"t{b}"])
    s1, d1 = turbo.search_phrase(phrases, k=10, slop=0)
    s2, d2 = turbo.search_phrase(phrases, k=10, slop=0)   # warm/degraded
    _assert_identical((s1, d1), (s2, d2), "degraded warm vs cold call")
    cold, _ = _engine(fp, len(lens), cold_df=1 << 30)
    s3, d3 = cold.search_phrase(phrases, k=10, slop=0)
    _assert_identical((s1, d1), (s3, d3), "degraded vs all-cold host")
    assert turbo.stats["degraded"] > 0, "degradation never exercised"


def test_sloppy_phrase_takes_host_path(corpus):
    """slop > 0 must bypass the adjacency columns and still agree with
    the uncached engine."""
    fp, lens, tokens, bounds, rng = corpus
    phrases = [["t0", "t1"], ["t1", "t0"], ["t2", "t5"]]
    dev, _ = _engine(fp, len(lens), cold_df=5)
    s1, d1 = dev.search_phrase(phrases, k=10, slop=2)
    assert dev.stats["phrase_builds"] == 0, "slop>0 built adjacency columns"
    cold, _ = _engine(fp, len(lens), cold_df=1 << 30)
    s2, d2 = cold.search_phrase(phrases, k=10, slop=2)
    _assert_identical((s1, d1), (s2, d2), "slop-2 device-eng vs all-cold")

"""Continuous-batching dispatch scheduler differential suite (PR 10).

The AdaptiveDispatchScheduler replaces the fixed-window coalescer as the
serving dispatch path; the contracts under test:

- merged rows are BIT-identical to solo execution across bucket shapes,
  engines (turbo + blockmax on the interpret-mode CPU mesh), and under
  injected device faults (PR 5 containment semantics);
- SLA tiers: an interactive query never waits past its budget behind a
  deep bulk backlog (the interactive deadline triggers the flush, bulk
  rides the pad slack);
- double buffering: a second batch dispatches while the first batch's
  waiter is still demuxing (slot-1 held), and does NOT with one slot;
- poison-batch solo retry parity with the coalescer;
- `ES_TPU_SCHED_MODE=legacy` routes through the old coalescer and
  `ES_TPU_COALESCE_US=0` disables batching in both modes.
"""

import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.common import faults, metrics
from elasticsearch_tpu.common.errors import DeviceFaultError
from elasticsearch_tpu.threadpool import ThreadPool, tier_for_request
from elasticsearch_tpu.threadpool.coalescer import default_coalescer
from elasticsearch_tpu.threadpool.scheduler import (
    DEFAULT_BUCKETS, TIER_BULK, TIER_INTERACTIVE, AdaptiveDispatchScheduler,
    _Lane, _parse_buckets, _Waiter, activate_tier, current_tier,
    default_scheduler, scheduler_stats, serving_dispatch,
)

pytestmark = [pytest.mark.multidevice]

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
         "theta", "iota", "kappa", "lam", "mu", "nu", "xi", "omicron", "pi"]

QUERIES = [["alpha"], ["beta", "gamma"], ["delta"], ["pi", "omicron"],
           ["mu", "nu", "xi"], ["kappa"], ["theta", "iota"], ["zeta", "eta"]]


def _build_index(monkeypatch, *, turbo: bool, uuid: str):
    from elasticsearch_tpu.cluster.state import IndexMetadata
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService

    if turbo:
        monkeypatch.setenv("ES_TPU_FORCE_TURBO", "1")
        monkeypatch.setenv("ES_TPU_TURBO_COLD_DF", "8")
    meta = IndexMetadata(
        index="sched_" + uuid, uuid=uuid, settings=Settings({}),
        mappings={"properties": {"body": {"type": "text"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(99)
    for i in range(320):
        words = rng.choice(WORDS, size=int(rng.integers(3, 16)))
        svc.index_doc(str(i), {"body": " ".join(words)})
        if i == 140:
            svc.refresh()
    for i in range(0, 50, 9):
        svc.delete_doc(str(i))
    svc.refresh()
    return svc


def _concurrent_sched(sched, eng, queries, k=10, tiers=None, fault_logs=None):
    """Each query on its own thread, all released together; returns
    (results, errors) aligned with `queries`."""
    results = [None] * len(queries)
    errors = [None] * len(queries)
    barrier = threading.Barrier(len(queries))

    def worker(i, q):
        try:
            barrier.wait(timeout=10)
            results[i] = sched.dispatch(
                eng, [q], k,
                tier=tiers[i] if tiers else None,
                fault_log=fault_logs[i] if fault_logs else None)
        except BaseException as e:  # noqa: BLE001 — asserted by callers
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i, q))
               for i, q in enumerate(queries)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    return results, errors


def _assert_rows_equal(got, want, ctx):
    gs, gp, go = got
    ws, wp, wo = want
    assert np.array_equal(gs, ws), ctx
    assert np.array_equal(gp, wp), ctx
    assert np.array_equal(go, wo), ctx


class _StubEngine:
    """search_many stub: deterministic per-query rows; optionally raises
    on merged batches / a poisoned query term / blocks on a gate."""

    def __init__(self, fail_merged=False, poison=None):
        self.fail_merged = fail_merged
        self.poison = poison
        self.calls = []

    def search_many(self, batches, k=10, check=None):
        qs = batches[0]
        self.calls.append(len(qs))
        if self.fail_merged and len(qs) > 1:
            raise DeviceFaultError("poisoned merged batch",
                                   site="turbo_sweep")
        out_s = np.zeros((len(qs), k), np.float32)
        out_p = np.zeros((len(qs), k), np.int32)
        out_o = np.zeros((len(qs), k), np.int32)
        for i, q in enumerate(qs):
            if self.poison is not None and self.poison in q:
                raise DeviceFaultError(f"query {q} is poison",
                                       site="turbo_sweep")
            out_s[i, 0] = float(len(q[0])) + 1.0
            out_o[i, 0] = len(q[0])
        return [(out_s, out_p, out_o)]


# ---------------------------------------------------------------------------
# knob parsing + SLA-tier classification and propagation
# ---------------------------------------------------------------------------


def test_parse_buckets_knob():
    assert _parse_buckets("1,4,16,64,256") == (1, 4, 16, 64, 256)
    assert _parse_buckets(" 16, 4 ,4,1 ") == (1, 4, 16)     # dedup + sort
    assert _parse_buckets("8") == (8,)
    # malformed / empty / non-positive specs fall back to the default
    assert _parse_buckets("banana") == DEFAULT_BUCKETS
    assert _parse_buckets("") == DEFAULT_BUCKETS
    assert _parse_buckets("0,-4") == DEFAULT_BUCKETS
    assert _parse_buckets("-4,0,2") == (2,)                 # keeps positives


def test_tier_for_request_classification():
    assert tier_for_request("POST", "/idx/_search") == TIER_INTERACTIVE
    assert tier_for_request("GET", "/idx/_doc/1") == TIER_INTERACTIVE
    assert tier_for_request("GET", "/idx/_mget") == TIER_INTERACTIVE
    # batch/scan-shaped search endpoints default to bulk
    assert tier_for_request("POST", "/_msearch") == TIER_BULK
    assert tier_for_request("POST", "/_search/scroll") == TIER_BULK
    assert tier_for_request("POST", "/idx/_async_search") == TIER_BULK
    assert tier_for_request("GET", "/idx/_rank_eval") == TIER_BULK
    # non-search stages are bulk
    assert tier_for_request("POST", "/idx/_bulk") == TIER_BULK
    assert tier_for_request("GET", "/_cluster/health") == TIER_BULK
    # an explicit sla param always wins; junk values are ignored
    assert tier_for_request("POST", "/idx/_search",
                            {"sla": "bulk"}) == TIER_BULK
    assert tier_for_request("POST", "/idx/_bulk",
                            {"sla": "interactive"}) == TIER_INTERACTIVE
    assert tier_for_request("POST", "/idx/_search",
                            {"sla": "platinum"}) == TIER_INTERACTIVE


def test_tier_context_rides_pool_submissions():
    assert current_tier() == TIER_INTERACTIVE        # safe default
    with activate_tier(TIER_BULK):
        assert current_tier() == TIER_BULK
        with activate_tier(None):                    # unknown: passthrough
            assert current_tier() == TIER_BULK
        with activate_tier(TIER_INTERACTIVE):
            assert current_tier() == TIER_INTERACTIVE
        assert current_tier() == TIER_BULK
    assert current_tier() == TIER_INTERACTIVE

    # the submitter's tier crosses the executor thread hop like the trace
    pool = ThreadPool(sizes={"search": 1})
    try:
        with activate_tier(TIER_BULK):
            task = pool.submit("search", current_tier)
        assert task.get(timeout=10) == TIER_BULK
        assert pool.submit("search", current_tier).get(timeout=10) \
            == TIER_INTERACTIVE
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# bucket selection (white-box: the flush decision function)
# ---------------------------------------------------------------------------


def _waiter(nq, tier, age, now):
    w = _Waiter([["q"]] * nq, tier)
    w.enqueued = now - age
    return w


def test_build_batch_flush_rules():
    sched = AdaptiveDispatchScheduler(buckets=(1, 4, 16),
                                      interactive_us=1000.0,
                                      bulk_us=8000.0)
    lane = _Lane(object(), 10, ("e", 10), inflight=2)
    now = time.monotonic()

    # nothing due, top bucket not full: keep waiting
    lane.queue = [_waiter(1, TIER_BULK, 0.001, now)]
    batch, depth = sched._build_batch(lane, now)
    assert batch is None and depth == 1 and len(lane.queue) == 1

    # one interactive past its 1ms budget flushes alone in bucket 1; the
    # not-yet-due bulk waiter stays parked (no slack in a 1-wide bucket)
    lane.queue = [_waiter(1, TIER_BULK, 0.001, now),
                  _waiter(1, TIER_INTERACTIVE, 0.002, now)]
    batch, depth = sched._build_batch(lane, now)
    assert depth == 2 and batch.bucket == 1
    assert [w.tier for w in batch.waiters] == [TIER_INTERACTIVE]
    assert [w.tier for w in lane.queue] == [TIER_BULK]

    # a 2-query due waiter needs bucket 4; parked bulk singles back-fill
    # the pad slack FIFO instead of widening the bucket
    lane.queue = [_waiter(1, TIER_BULK, 0.001, now),
                  _waiter(1, TIER_BULK, 0.0005, now),
                  _waiter(1, TIER_BULK, 0.0001, now),
                  _waiter(2, TIER_INTERACTIVE, 0.002, now)]
    batch, depth = sched._build_batch(lane, now)
    assert depth == 5 and batch.bucket == 4
    assert len(batch.queries) == 4                  # 2 due + 2 riders
    assert batch.waiters[0].tier == TIER_INTERACTIVE
    assert len(lane.queue) == 1                     # third bulk overflows

    # top bucket full flushes everything even with nothing due
    lane.queue = [_waiter(4, TIER_BULK, 0.0001, now) for _ in range(4)]
    batch, depth = sched._build_batch(lane, now)
    assert depth == 16 and batch.bucket == 16
    assert len(batch.queries) == 16 and not lane.queue

    # due backlog wider than the top bucket: flush caps at the ladder top
    # and the overflow stays due for an immediate next flush
    lane.queue = [_waiter(4, TIER_INTERACTIVE, 0.01, now) for _ in range(5)]
    batch, depth = sched._build_batch(lane, now)
    assert depth == 20 and batch.bucket == 16
    assert len(batch.queries) == 16 and len(lane.queue) == 1


# ---------------------------------------------------------------------------
# bit-identity with solo execution (real engines, interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("turbo", [True, False], ids=["turbo", "blockmax"])
def test_scheduled_rows_bit_identical_to_solo(monkeypatch, turbo):
    monkeypatch.setenv("ES_TPU_COALESCE_US", "300000")
    svc = _build_index(monkeypatch, turbo=turbo, uuid="u_sc1" + str(turbo))
    try:
        eng = svc.serving.snapshot().engine("body")
        assert eng.kind == ("turbo" if turbo else "blockmax")
        solo = [eng.search_many([[q]], k=10)[0] for q in QUERIES]

        # generous budgets + a ladder topping at len(QUERIES): all eight
        # concurrent singles merge into exactly ONE bucket-8 flush
        sched = AdaptiveDispatchScheduler(buckets=(len(QUERIES),),
                                          interactive_us=400000.0,
                                          bulk_us=400000.0)
        results, errors = _concurrent_sched(sched, eng, QUERIES)
        assert errors == [None] * len(QUERIES)
        for q, got, want in zip(QUERIES, results, solo):
            _assert_rows_equal(got, want, f"merged {q}")
        st = sched.stats()
        assert st["sched_dispatches"] == 1
        assert st["sched_queries"] == len(QUERIES)
        assert st["largest_batch"] == len(QUERIES)
        assert st["bucket_counts"] == {str(len(QUERIES)): 1}

        # zero budgets: every waiter is due on arrival, so flushes split
        # across small buckets of the default ladder — still bit-identical
        sched0 = AdaptiveDispatchScheduler(buckets=DEFAULT_BUCKETS,
                                           interactive_us=0.0, bulk_us=0.0)
        results0, errors0 = _concurrent_sched(sched0, eng, QUERIES)
        assert errors0 == [None] * len(QUERIES)
        for q, got, want in zip(QUERIES, results0, solo):
            _assert_rows_equal(got, want, f"split {q}")
        st0 = sched0.stats()
        assert st0["sched_queries"] == len(QUERIES)
        assert 1 <= st0["sched_dispatches"] <= len(QUERIES)
    finally:
        svc.close()


def test_scheduler_primes_engine_bucket_shapes(monkeypatch):
    monkeypatch.setenv("ES_TPU_COALESCE_US", "300000")
    svc = _build_index(monkeypatch, turbo=True, uuid="u_sc_prime")
    try:
        eng = svc.serving.snapshot().engine("body")
        base = set(eng.qc_sizes)
        pad_before = metrics.summary("coalesce_pad_ratio")["count"]
        sched = AdaptiveDispatchScheduler(buckets=(1, 4, 16, 64),
                                          interactive_us=0.0, bulk_us=0.0)
        got = sched.dispatch(eng, [QUERIES[0]], 10)
        # the ladder lands in the engine's compiled-width cache, rounded
        # up to ROWS_PER_STEP multiples like the constructor's qc_sizes
        assert {8, 16, 64} <= set(eng.qc_sizes)
        assert set(eng.qc_sizes) >= base
        assert list(eng.qc_sizes) == sorted(set(eng.qc_sizes))
        # pad-waste is recorded at the device-dispatch site for the
        # scheduler path too (the engine now exposes qc_sizes)
        assert metrics.summary("coalesce_pad_ratio")["count"] > pad_before
        _assert_rows_equal(got, eng.search_many([[QUERIES[0]]], k=10)[0],
                           "primed")
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# SLA tiers: interactive latency under a deep bulk backlog
# ---------------------------------------------------------------------------


def test_interactive_budget_flushes_past_parked_bulk():
    eng = _StubEngine()
    # bulk may wait 10s; interactive must flush within ~8ms
    sched = AdaptiveDispatchScheduler(buckets=(4,),
                                      interactive_us=8000.0,
                                      bulk_us=10_000_000.0, inflight=2)
    results = [None] * 4
    done = [threading.Event() for _ in range(4)]

    def run(i, tier):
        results[i] = sched.dispatch(eng, [[f"q{i}"]], 10, tier=tier)
        done[i].set()

    threads = [threading.Thread(target=run, args=(i, TIER_BULK))
               for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.1)
    assert eng.calls == []                  # bulk parked, nothing flushed
    t0 = time.monotonic()
    run(3, TIER_INTERACTIVE)
    interactive_wait = time.monotonic() - t0
    # the interactive deadline triggered the flush, and the parked bulk
    # waiters rode the pad slack of its bucket instead of waiting out
    # their own 10s budget
    assert interactive_wait < 2.0
    for i in range(3):
        assert done[i].wait(5), f"bulk waiter {i} still parked"
    assert eng.calls == [4]                 # ONE merged bucket-4 flush
    for i in range(4):
        assert float(results[i][0][0, 0]) == len(f"q{i}") + 1.0
    st = sched.stats()
    assert st["tiers"][TIER_INTERACTIVE]["dispatches"] == 1
    assert st["tiers"][TIER_BULK]["dispatches"] == 3
    assert st["bucket_counts"] == {"4": 1}


# ---------------------------------------------------------------------------
# double buffering: two in-flight slots overlap demux with the next sweep
# ---------------------------------------------------------------------------


def _blocked_waiter(sched, eng):
    """Dispatch one query whose boundary check parks: returns (thread,
    parked_event, release_event, result_box). The entry check is call 1;
    the boundary check (call 2) blocks — the waiter holds its batch's
    in-flight slot until released."""
    parked = threading.Event()
    release = threading.Event()
    box = {}
    calls = {"n": 0}

    def check():
        calls["n"] += 1
        if calls["n"] == 2:
            parked.set()
            assert release.wait(20)

    def run():
        box["rows"] = sched.dispatch(eng, [["aa"]], 10, check=check)

    t = threading.Thread(target=run)
    t.start()
    return t, parked, release, box


def test_double_buffer_dispatches_while_demux_in_flight():
    eng = _StubEngine()
    sched = AdaptiveDispatchScheduler(buckets=(1,), interactive_us=0.0,
                                      bulk_us=0.0, inflight=2)
    t_a, parked, release, box = _blocked_waiter(sched, eng)
    assert parked.wait(10)                  # batch A done, slot 1 held
    assert sched.stats()["inflight"] == 1
    # batch B dispatches and completes on slot 2 while A is still demuxing
    rows_b = sched.dispatch(eng, [["bbb"]], 10)
    assert float(rows_b[0][0, 0]) == 4.0
    assert t_a.is_alive()
    st = sched.stats()
    assert st["max_inflight"] == 2          # the overlap was real
    release.set()
    t_a.join(timeout=10)
    assert not t_a.is_alive()
    assert float(box["rows"][0][0, 0]) == 3.0
    assert sched.stats()["inflight"] == 0


def test_single_slot_serializes_behind_unconsumed_batch():
    eng = _StubEngine()
    sched = AdaptiveDispatchScheduler(buckets=(1,), interactive_us=0.0,
                                      bulk_us=0.0, inflight=1)
    t_a, parked, release, box = _blocked_waiter(sched, eng)
    assert parked.wait(10)
    done_b = threading.Event()
    rows = {}

    def run_b():
        rows["b"] = sched.dispatch(eng, [["bbb"]], 10)
        done_b.set()

    t_b = threading.Thread(target=run_b)
    t_b.start()
    # with ONE slot, B's device dispatch must wait for A's consume
    assert not done_b.wait(0.4)
    assert eng.calls == [1]
    release.set()
    assert done_b.wait(10)
    t_a.join(timeout=10)
    t_b.join(timeout=10)
    assert eng.calls == [1, 1]
    assert float(rows["b"][0][0, 0]) == 4.0
    assert sched.stats()["max_inflight"] == 1


# ---------------------------------------------------------------------------
# poison-batch containment parity with the coalescer
# ---------------------------------------------------------------------------


def test_poison_batch_retries_each_waiter_solo():
    eng = _StubEngine(fail_merged=True)
    sched = AdaptiveDispatchScheduler(buckets=(3,), interactive_us=400000.0,
                                      bulk_us=400000.0)
    queries = [["a"], ["bb"], ["ccc"]]
    results, errors = _concurrent_sched(sched, eng, queries)
    assert errors == [None, None, None]
    for q, r in zip(queries, results):
        assert float(r[0][0, 0]) == len(q[0]) + 1.0, q
    assert sched.stats()["sched_batch_retries"] == 1
    # one failed merged dispatch + one solo retry per waiter
    assert sorted(eng.calls) == [1, 1, 1, 3]


def test_poison_query_error_isolated_to_its_waiter():
    eng = _StubEngine(poison="bad")
    sched = AdaptiveDispatchScheduler(buckets=(3,), interactive_us=400000.0,
                                      bulk_us=400000.0)
    queries = [["good"], ["bad"], ["fine"]]
    results, errors = _concurrent_sched(sched, eng, queries)
    bad_i = queries.index(["bad"])
    for i, (r, e) in enumerate(zip(results, errors)):
        if i == bad_i:
            assert isinstance(e, DeviceFaultError) and r is None
        else:
            assert e is None
            assert float(r[0][0, 0]) == len(queries[i][0]) + 1.0
    assert sched.stats()["sched_batch_retries"] == 1


def test_all_retries_failing_surfaces_original_error():
    class _Dead:
        def search_many(self, batches, k=10, check=None):
            raise DeviceFaultError("engine is gone", site="turbo_sweep")

    sched = AdaptiveDispatchScheduler(buckets=(2,), interactive_us=400000.0,
                                      bulk_us=400000.0)
    results, errors = _concurrent_sched(sched, _Dead(), [["a"], ["b"]])
    assert results == [None, None]
    assert all(isinstance(e, DeviceFaultError) for e in errors)


@pytest.mark.faults
def test_scheduler_contains_injected_device_fault(monkeypatch):
    """ES_TPU_FAULTS-style device faults under a merged scheduler
    dispatch: the serving engine's fused dispatch faults AND any
    per-partition turbo_sweep fallback faults too, so PR 5 containment
    re-scores the work through the host tier — rows stay bit-identical
    and the FaultRecords are ferried to EVERY waiter's fault_log
    (coalescer parity)."""
    monkeypatch.setenv("ES_TPU_COALESCE_US", "300000")
    svc = _build_index(monkeypatch, turbo=True, uuid="u_sc_flt")
    try:
        eng = svc.serving.snapshot().engine("body")
        queries = QUERIES[:4]
        solo = [eng.search_many([[q]], k=10)[0] for q in queries]
        sched = AdaptiveDispatchScheduler(buckets=(4,),
                                          interactive_us=400000.0,
                                          bulk_us=400000.0)
        flogs = [[] for _ in queries]
        with faults.inject("fused_dispatch:raise@1;turbo_sweep:raisexinf"):
            results, errors = _concurrent_sched(sched, eng, queries,
                                                fault_logs=flogs)
        assert errors == [None] * len(queries)
        for q, got, want in zip(queries, results, solo):
            _assert_rows_equal(got, want, f"fault-contained {q}")
        for flog in flogs:
            assert flog, "fault records must reach every waiter"
            assert all(f.site in ("fused_dispatch", "turbo_sweep")
                       for f in flog)
        # contained, not retried: the engine absorbed the fault in-dispatch
        assert sched.stats()["sched_batch_retries"] == 0
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# mode routing: legacy shim + window-0 kill switch
# ---------------------------------------------------------------------------


def test_legacy_mode_routes_through_coalescer(monkeypatch):
    eng = _StubEngine()
    monkeypatch.setenv("ES_TPU_COALESCE_US", "0")   # direct: no threads
    monkeypatch.setenv("ES_TPU_SCHED_MODE", "legacy")
    co_before = default_coalescer().stats()["direct_dispatches"]
    sc_before = default_scheduler().stats()["direct_dispatches"]
    modes_before = scheduler_stats()["mode_dispatches"]
    serving_dispatch(eng, [["a"]], 10)
    assert default_coalescer().stats()["direct_dispatches"] == co_before + 1
    assert default_scheduler().stats()["direct_dispatches"] == sc_before
    st = scheduler_stats()
    assert st["mode"] == "legacy"
    assert st["mode_dispatches"]["legacy"] == modes_before["legacy"] + 1

    monkeypatch.setenv("ES_TPU_SCHED_MODE", "adaptive")
    serving_dispatch(eng, [["b"]], 10)
    assert default_scheduler().stats()["direct_dispatches"] == sc_before + 1
    assert default_coalescer().stats()["direct_dispatches"] == co_before + 1
    assert scheduler_stats()["mode_dispatches"]["adaptive"] \
        == modes_before["adaptive"] + 1
    assert eng.calls == [1, 1]


def test_window_zero_disables_batching_entirely(monkeypatch):
    eng = _StubEngine()
    monkeypatch.setenv("ES_TPU_COALESCE_US", "0")
    sched = AdaptiveDispatchScheduler(buckets=(8,))
    before = sched.stats()
    out = sched.dispatch(eng, [["a"]], 10)
    assert float(out[0][0, 0]) == 2.0
    st = sched.stats()
    assert st["direct_dispatches"] == before["direct_dispatches"] + 1
    assert st["sched_dispatches"] == before["sched_dispatches"]
    assert st["lanes"] == 0                 # no lane thread was started
    assert eng.calls == [1]


# ---------------------------------------------------------------------------
# serving path end to end through the adaptive scheduler
# ---------------------------------------------------------------------------


def test_serving_path_batches_through_scheduler(monkeypatch):
    """End to end through ServingContext.try_search in adaptive mode:
    concurrent REST-level singles return the same responses as solo
    execution and the process-default SCHEDULER (not the coalescer)
    reports the merged device dispatches."""
    svc = _build_index(monkeypatch, turbo=True, uuid="u_sc_e2e")
    try:
        bodies = [{"query": {"match": {"body": " ".join(q)}}}
                  for q in QUERIES]
        monkeypatch.setenv("ES_TPU_COALESCE_US", "0")
        want = [svc.serving.try_search(b, "query_then_fetch")
                for b in bodies]
        assert all(w is not None for w in want)

        monkeypatch.setenv("ES_TPU_SCHED_MODE", "adaptive")
        monkeypatch.setenv("ES_TPU_COALESCE_US", "300000")
        monkeypatch.setenv("ES_TPU_SCHED_BUCKETS", str(len(bodies)))
        monkeypatch.setenv("ES_TPU_SCHED_INTERACTIVE_US", "300000")
        monkeypatch.setenv("ES_TPU_SCHED_BULK_US", "300000")
        before = default_scheduler().stats()
        got = [None] * len(bodies)
        errors = []
        barrier = threading.Barrier(len(bodies))

        def worker(i, b):
            try:
                barrier.wait(timeout=10)
                got[i] = svc.serving.try_search(b, "query_then_fetch")
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i, b))
                   for i, b in enumerate(bodies)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        after = default_scheduler().stats()
        flushes = after["sched_dispatches"] - before["sched_dispatches"]
        merged = after["sched_queries"] - before["sched_queries"]
        assert merged == len(bodies)
        assert 1 <= flushes < len(bodies)   # real merging happened
        # no explicit tier: serving threads default to interactive
        assert after["tiers"][TIER_INTERACTIVE]["dispatches"] \
            - before["tiers"][TIER_INTERACTIVE]["dispatches"] == len(bodies)
        for b, g, w in zip(bodies, got, want):
            assert g is not None, b
            assert [h["_id"] for h in g["hits"]["hits"]] == \
                [h["_id"] for h in w["hits"]["hits"]], b
            assert [h["_score"] for h in g["hits"]["hits"]] == \
                [h["_score"] for h in w["hits"]["hits"]], b
            assert g["hits"]["total"] == w["hits"]["total"], b
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# bucket-ladder autotune (PR 16): knob unset -> ladder derived from the
# observed flush-time demand histograms
# ---------------------------------------------------------------------------


def test_derive_ladder_from_synthetic_histograms():
    from elasticsearch_tpu.threadpool.scheduler import _derive_ladder

    depth = {"count": 500, "p50": 4, "p90": 32, "p99": 64, "max": 200}
    # rungs at the depth percentiles + rounded-up max, anchored at 1
    assert _derive_ladder(depth, None) == (1, 4, 32, 64, 256)
    # low pad waste: no densification
    assert _derive_ladder(depth, {"count": 500, "p90": 0.1}) == \
        (1, 4, 32, 64, 256)
    # persistent pad waste adds geometric midpoints into the wide gaps
    assert _derive_ladder(depth, {"count": 500, "p90": 0.6}) == \
        (1, 2, 4, 16, 32, 64, 128, 256)
    # the cap bounds the largest compiled shape
    assert _derive_ladder({"count": 100, "p50": 1024, "p90": 2048,
                           "p99": 4096, "max": 4000}, None)[-1] == 512


def test_autotune_ladder_pins_synthetic_trace(monkeypatch):
    """Knob unset: the ladder stays at DEFAULT_BUCKETS until enough
    flushes are observed, then pins to the demand-derived rungs for a
    bimodal synthetic trace (singles + ~48-deep bursts) and caches."""
    monkeypatch.delenv("ES_TPU_SCHED_BUCKETS", raising=False)
    metrics.reset_for_tests()
    sched = AdaptiveDispatchScheduler()
    assert sched.ladder() == DEFAULT_BUCKETS      # under-observed
    for _ in range(100):
        metrics.observe("sched_queue_depth", 1)
    for _ in range(40):
        metrics.observe("sched_queue_depth", 48)
    lad = sched.ladder()
    assert lad == (1, 64)        # p50 bucket bound 1, burst bound 64
    assert sched.ladder() is lad or sched.ladder() == lad   # cached
    st = sched.stats()
    assert st["bucket_source"] == "auto"
    assert st["buckets"] == [1, 64]
    # an explicit knob immediately overrides the autotuner
    monkeypatch.setenv("ES_TPU_SCHED_BUCKETS", "2,8")
    assert sched.ladder() == (2, 8)
    assert sched.stats()["bucket_source"] == "knob"


def test_prime_reprimes_on_ladder_change():
    """The primed-ladder guard: an unchanged ladder never re-primes, a
    changed one pushes the new rungs into the engine's compiled widths
    before any flush can use them."""

    class _Eng:
        def __init__(self):
            self.calls = []

        def extend_qc_sizes(self, sizes):
            self.calls.append(tuple(sizes))

    sched = AdaptiveDispatchScheduler(buckets=(1, 4))
    e = _Eng()
    sched._prime_engine(e)
    sched._prime_engine(e)                        # no ladder change
    assert e.calls == [(1, 4)]
    sched._buckets = (1, 4, 32)                   # ladder re-derived
    sched._prime_engine(e)
    assert e.calls == [(1, 4), (1, 4, 32)]

"""Device-fault containment differential suite (PR 5).

Deterministic faults (common/faults.py) are injected at every named
dispatch site and the contract is BIT-identity with the no-fault host
reference: containment re-scores the faulted partition/query through the
exact host tier (the same `_exact_merge` route the certificate path
lands in), so a fault changes counters and `_shards` accounting — never
results.

Also pins the circuit-breaker lifecycle (K consecutive faults open ->
zero device dispatches while open -> half-open probe -> closed), the
coalescer's poison-batch solo retry, and the serving-level
`allow_partial_search_results` / `timeout` semantics.

Runs on the host-simulated 8-device CPU mesh from tests/conftest.py
(interpret mode, ES_TPU_FORCE_TURBO=1 where the REST path is involved).
"""

import logging
import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.common import faults
from elasticsearch_tpu.common.errors import (
    DeviceFaultError, HbmOomError, SearchPhaseExecutionError,
)
from elasticsearch_tpu.common.faults import FaultSpecError
from elasticsearch_tpu.common.health import EngineHealth, node_health_stats
from elasticsearch_tpu.index.segment import build_field_postings
from elasticsearch_tpu.parallel.spmd import build_stacked_bm25
from elasticsearch_tpu.parallel.turbo import TurboBM25

pytestmark = [pytest.mark.faults, pytest.mark.multidevice]


class _Seg:
    def __init__(self, n_docs, fp):
        self.n_docs = n_docs
        self.postings = {"body": fp}
        self.vectors = {}


def _pcorpus(n_docs, vocab, seed):
    rng = np.random.default_rng(seed)
    probs = 1.0 / np.arange(1, vocab + 1) ** 1.1
    probs /= probs.sum()
    lens = rng.integers(4, 24, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum()), p=probs).astype(np.int64)
    tok_docs = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    bounds = np.concatenate([[0], np.cumsum(lens)])
    tok_pos = (np.arange(len(tokens), dtype=np.int64)
               - np.repeat(bounds[:-1], lens))
    return build_field_postings("body", lens, tok_docs, tokens,
                                [f"t{i}" for i in range(vocab)],
                                token_pos=tok_pos)


def _turbo(fp, n_docs, cold_df=5, hbm=64 << 20):
    stacked = build_stacked_bm25([_Seg(n_docs, fp)], "body", serve_only=True)
    return TurboBM25(stacked, hbm_budget_bytes=hbm, cold_df=cold_df)


def _engine(parts, mesh=True):
    from elasticsearch_tpu.search.serving import TurboEngine, _turbo_mesh

    turbos = [_turbo(fp, n) for n, fp in parts]
    return TurboEngine(turbos,
                       mesh=_turbo_mesh(len(turbos)) if mesh else None)


def _host_many(eng, batch, k):
    per = [t.search_many_host([batch], k=k)[0] for t in eng.turbos]
    return eng._merge3(per, len(batch), k)


def _host_bool(eng, specs, k):
    per = [t.search_bool_host(specs, k=k) for t in eng.turbos]
    return eng._merge3(per, len(specs), k)


def _assert_rows_equal(got, want, ctx):
    for g, w, name in zip(got, want, ("scores", "parts", "ords")):
        assert np.array_equal(np.asarray(g), np.asarray(w)), (ctx, name)


BATCH = [["t1", "t3"], ["t2", "t5"], ["t0", "t7"], ["t4", "t1"],
         ["t6", "t2"]]
SPECS = [
    {"must": [("t1", 1.0)], "should": [("t3", 1.0)]},
    {"must": [("t0", 1.0), ("t2", 1.5)]},
    {"must": [("t4", 1.0)], "filter": ["t1"]},
]
K = 10


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------


def test_parse_spec_full_grammar():
    cl = faults.parse_spec(
        "turbo_sweep#1:raise@2x3;fused_dispatch:oom~0.5;"
        "merge_kernel:hang=0.01;column_upload:raisexinf")
    assert [(c.site, c.part, c.mode) for c in cl] == [
        ("turbo_sweep", 1, "raise"), ("fused_dispatch", None, "oom"),
        ("merge_kernel", None, "hang"), ("column_upload", None, "raise")]
    assert (cl[0].nth, cl[0].count) == (2, 3)
    assert cl[1].prob == 0.5 and cl[1].rng is not None
    assert cl[2].arg == 0.01
    assert cl[3].count == float("inf")


@pytest.mark.parametrize("bad", [
    "not_a_site:raise",          # unknown site
    "turbo_sweep:explode",       # unknown mode
    "turbo_sweep#x:raise",       # bad partition
    "turbo_sweep",               # missing mode
    "turbo_sweep:raise@zz",      # bad nth
])
def test_parse_spec_rejects(bad):
    with pytest.raises(FaultSpecError):
        faults.parse_spec(bad)


def test_fault_point_nth_count_and_part_scope():
    with faults.inject("turbo_sweep#1:raise@2x2"):
        faults.fault_point("turbo_sweep", 0)      # wrong partition: never
        faults.fault_point("merge_kernel", 1)     # wrong site: never
        faults.fault_point("turbo_sweep", 1)      # call 1 < nth
        for _ in range(2):                        # calls 2, 3 fire (x2)
            with pytest.raises(DeviceFaultError) as ei:
                faults.fault_point("turbo_sweep", 1)
            assert ei.value.site == "turbo_sweep" and ei.value.part == 1
        faults.fault_point("turbo_sweep", 1)      # count exhausted
    faults.fault_point("turbo_sweep", 1)          # restored on exit


def test_oom_mode_and_device_error_translation():
    with faults.inject("turbo_sweep:oom"):
        with pytest.raises(HbmOomError):
            faults.fault_point("turbo_sweep")
    with pytest.raises(HbmOomError):
        with faults.device_errors("turbo_sweep", 2):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory on chip")
    with pytest.raises(ValueError):               # non-device errors pass
        with faults.device_errors("turbo_sweep"):
            raise ValueError("not a device problem")


# ---------------------------------------------------------------------------
# engine-level differentials: fault at every site, results bit-identical
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def eng2():
    """Warm 2-partition fused engine for sites that fire post-build."""
    eng = _engine([(900, _pcorpus(900, 40, 1)), (1300, _pcorpus(1300, 32, 2))])
    eng.search_many([BATCH], k=K)      # build columns, compile dispatch
    return eng


def test_solo_sweep_fault_bit_identical():
    eng = _engine([(700, _pcorpus(700, 40, 7))], mesh=False)
    want = _host_many(eng, BATCH, K)
    for spec in ("turbo_sweep:raise@1", "turbo_sweep:oom@1"):
        flog = []
        with faults.inject(spec):
            got = eng.search_many([BATCH], k=K, fault_log=flog)[0]
        _assert_rows_equal(got, want, spec)
        assert flog and flog[0].partition == 0 and flog[0].recovered
    assert eng.stats["health_device_faults"] >= 2


def test_fused_dispatch_fault_bit_identical(eng2):
    want = _host_many(eng2, BATCH, K)
    flog = []
    with faults.inject("fused_dispatch:raise@1"):
        got = eng2.search_many([BATCH], k=K, fault_log=flog)[0]
    _assert_rows_equal(got, want, "fused_dispatch")
    assert any(f.site == "fused_dispatch" for f in flog)


def test_partition_column_fault_isolated():
    # FRESH engine: the fault must fire during the first column build
    eng = _engine([(600, _pcorpus(600, 40, 3)), (800, _pcorpus(800, 32, 4))])
    want = _host_many(eng, BATCH, K)
    flog = []
    with faults.inject("column_upload#1:raise@1"):
        got = eng.search_many([BATCH], k=K, fault_log=flog)[0]
    _assert_rows_equal(got, want, "column_upload#1")
    assert any(f.partition == 1 for f in flog)
    # the faulted partition recovers: a clean retry serves device-side
    # again off the rebuilt cache and still matches
    _assert_rows_equal(eng.search_many([BATCH], k=K)[0], want, "recovered")


def test_bool_and_phrase_under_partition_fault():
    eng = _engine([(600, _pcorpus(600, 40, 5)), (800, _pcorpus(800, 32, 6))])
    want = _host_bool(eng, SPECS, K)
    with faults.inject("column_upload#0:raise@1"):
        got = eng.search_bool(SPECS, k=K)
    _assert_rows_equal(got, want, "bool under column fault")
    phrases = [["t0", "t1"], ["t2", "t0"]]
    want_p = _host_bool(
        eng, [{"phrases": [(p, 0, 1.0)]} for p in phrases], K)
    with faults.inject("turbo_sweep:raisexinf"):
        got_p = eng.search_phrase(phrases, k=K, slop=0)
    _assert_rows_equal(got_p, want_p, "phrase under sweep fault")


def test_merge_kernel_fault_degrades_to_host_merge(eng2):
    want = _host_many(eng2, BATCH, K)
    h0 = eng2.merge_stats["merge_host"]
    flog = []
    with faults.inject("merge_kernel:raise@1"):
        got = eng2.search_many([BATCH], k=K, fault_log=flog)[0]
    _assert_rows_equal(got, want, "merge_kernel")
    assert eng2.merge_stats["merge_host"] == h0 + 1
    assert any(f.site == "merge_kernel" for f in flog)


def test_blockmax_fault_point_raises():
    # the BlockMax engine has no internal host tier: its fault surface
    # raises (serving catches it, records the fault on the engine's
    # circuit, and falls back to the dense executor)
    with faults.inject("blockmax_pass:raise@1"):
        with pytest.raises(DeviceFaultError):
            faults.fault_point("blockmax_pass")


# ---------------------------------------------------------------------------
# circuit breaker lifecycle
# ---------------------------------------------------------------------------


def test_circuit_opens_after_trip_n_and_probe_restores():
    eng = _engine([(700, _pcorpus(700, 40, 9))], mesh=False)
    eng.health = EngineHealth("turbo", trip_n=2, backoff_ms=40)
    t = eng.turbos[0]
    want = _host_many(eng, BATCH, K)
    eng.search_many([BATCH], k=K)                      # warm, clean
    with faults.inject("turbo_sweep:raisexinf"):
        for i in range(2):                             # trip the breaker
            _assert_rows_equal(eng.search_many([BATCH], k=K)[0], want,
                               f"contained fault {i}")
        assert eng.health.state == "open"
        d0 = t.stats["dispatches"]
        # while open: host tier serves, ZERO device dispatches
        _assert_rows_equal(eng.search_many([BATCH], k=K)[0], want, "open")
        assert t.stats["dispatches"] == d0
        assert eng.health.counters["fallback_queries"] >= len(BATCH)
    time.sleep(0.06)                                   # past backoff
    _assert_rows_equal(eng.search_many([BATCH], k=K)[0], want, "probe")
    assert eng.health.state == "closed"
    c = eng.health.counters
    assert c["circuit_opens"] == 1
    assert c["probes"] == 1 and c["probe_successes"] == 1
    trans = list(eng.health._transitions)
    assert trans == ["closed->open", "open->half_open",
                     "half_open->closed"]


def test_failed_probe_reopens_with_exponential_backoff():
    h = EngineHealth("x", trip_n=1, backoff_ms=10)
    h.record_fault(DeviceFaultError("boom"))
    assert h.state == "open" and h.backoff_ms == 10
    for i in range(1, 8):
        h._retry_at = 0.0                  # make the probe due now
        assert h.allow_device()            # half-open probe admitted
        assert not h.allow_device()        # only ONE probe in flight
        h.record_fault(DeviceFaultError("boom"))
        assert h.state == "open"
        assert h.backoff_ms == min(10 * 2 ** i, 320)
    assert h.counters["circuit_reopens"] == 7
    h._retry_at = 0.0
    assert h.allow_device()
    h.record_success()
    assert h.state == "closed" and h.backoff_ms == 10


def test_health_visible_in_node_stats_and_handler():
    h = EngineHealth("visible_test", trip_n=1, backoff_ms=10)
    h.record_fault(DeviceFaultError("boom"))
    node = node_health_stats()
    mine = [e for e in node["engines"] if e["name"] == "visible_test"]
    assert mine and mine[0]["state"] == "open"
    assert node["open_circuits"] >= 1
    assert node["device_faults"] >= 1
    from elasticsearch_tpu.rest.handlers import _tpu_health_stats

    full = _tpu_health_stats()
    for key in ("engines", "open_circuits", "device_faults",
                "fastpath_reject_error", "shard_fault_recoveries",
                "coalesce_batch_retries"):
        assert key in full


# ---------------------------------------------------------------------------
# coalescer: poison-batch solo retry
# ---------------------------------------------------------------------------


class _StubEngine:
    """search_many stub: deterministic per-query rows; raises on merged
    batches and/or on a poisoned query term."""

    def __init__(self, fail_merged=False, poison=None):
        self.fail_merged = fail_merged
        self.poison = poison
        self.calls = []

    def search_many(self, batches, k=10, check=None):
        qs = batches[0]
        self.calls.append(len(qs))
        if self.fail_merged and len(qs) > 1:
            raise DeviceFaultError("poisoned merged batch",
                                   site="turbo_sweep")
        out_s = np.zeros((len(qs), k), np.float32)
        out_p = np.zeros((len(qs), k), np.int32)
        out_o = np.zeros((len(qs), k), np.int32)
        for i, q in enumerate(qs):
            if self.poison is not None and self.poison in q:
                raise DeviceFaultError(f"query {q} is poison",
                                       site="turbo_sweep")
            out_s[i, 0] = float(len(q[0])) + 1.0
            out_o[i, 0] = len(q[0])
        return [(out_s, out_p, out_o)]


def _concurrent(co, eng, queries, k=10):
    results = [None] * len(queries)
    errors = [None] * len(queries)
    barrier = threading.Barrier(len(queries))

    def worker(i, q):
        try:
            barrier.wait(timeout=10)
            results[i] = co.dispatch(eng, [q], k)
        except BaseException as e:  # noqa: BLE001 — asserted below
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i, q))
               for i, q in enumerate(queries)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    return results, errors


def test_poison_batch_retries_each_waiter_solo():
    from elasticsearch_tpu.threadpool.coalescer import DispatchCoalescer

    eng = _StubEngine(fail_merged=True)
    co = DispatchCoalescer(window_us=200000)
    queries = [["a"], ["bb"], ["ccc"]]
    results, errors = _concurrent(co, eng, queries)
    assert errors == [None, None, None]
    for q, r in zip(queries, results):
        assert float(r[0][0, 0]) == len(q[0]) + 1.0, q
    st = co.stats()
    assert st["coalesce_batch_retries"] == 1
    # one failed merged dispatch + one solo retry per waiter
    assert sorted(eng.calls) == [1, 1, 1, 3]


def test_poison_query_error_isolated_to_its_waiter():
    from elasticsearch_tpu.threadpool.coalescer import DispatchCoalescer

    eng = _StubEngine(poison="bad")
    co = DispatchCoalescer(window_us=200000)
    # the poison term kills merged AND its own solo retry; peers succeed
    queries = [["good"], ["bad"], ["fine"]]
    results, errors = _concurrent(co, eng, queries)
    bad_i = queries.index(["bad"])
    for i, (r, e) in enumerate(zip(results, errors)):
        if i == bad_i:
            assert isinstance(e, DeviceFaultError) and r is None
        else:
            assert e is None
            assert float(r[0][0, 0]) == len(queries[i][0]) + 1.0
    assert co.stats()["coalesce_batch_retries"] == 1


def test_all_retries_failing_surfaces_original_error():
    from elasticsearch_tpu.threadpool.coalescer import DispatchCoalescer

    class _Dead:
        def search_many(self, batches, k=10, check=None):
            raise DeviceFaultError("engine is gone", site="turbo_sweep")

    co = DispatchCoalescer(window_us=200000)
    results, errors = _concurrent(co, _Dead(), [["a"], ["b"]])
    assert results == [None, None]
    assert all(isinstance(e, DeviceFaultError) for e in errors)


# ---------------------------------------------------------------------------
# serving path: _shards accounting, allow_partial_search_results, timeout
# ---------------------------------------------------------------------------

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
         "theta", "iota", "kappa", "lam", "mu"]


@pytest.fixture()
def turbo_svc(monkeypatch):
    from elasticsearch_tpu.cluster.state import IndexMetadata
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService

    monkeypatch.setenv("ES_TPU_FORCE_TURBO", "1")
    monkeypatch.setenv("ES_TPU_TURBO_COLD_DF", "8")
    meta = IndexMetadata(
        index="faults_t", uuid="u_faults", settings=Settings({}),
        mappings={"properties": {"body": {"type": "text"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(21)
    for i in range(260):
        words = rng.choice(WORDS, size=int(rng.integers(3, 14)))
        svc.index_doc(str(i), {"body": " ".join(words)})
        if i == 120:
            svc.refresh()          # two segments -> two partitions
    svc.refresh()
    yield svc
    svc.close()


def _hits(resp):
    return [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]


def test_apsr_false_turns_fault_into_request_error(turbo_svc):
    body = {"query": {"match": {"body": "alpha beta"}},
            "allow_partial_search_results": False}
    with faults.inject("column_upload:raise@1"):
        with pytest.raises(SearchPhaseExecutionError) as ei:
            turbo_svc.search(body)
    assert "allow_partial_search_results" in str(ei.value)


def test_recovered_fault_reported_in_shards(turbo_svc):
    from elasticsearch_tpu.search.serving import serving_fault_stats

    body = {"query": {"match": {"body": "alpha beta"}}}
    # clean fast-path reference via try_search (bypasses the request
    # cache); the faulted run must match it BITWISE — the host tier
    # rescores the faulted partition through the same exact route
    want = turbo_svc.serving.try_search(body, "query_then_fetch")
    r0 = serving_fault_stats()["shard_fault_recoveries"]
    with faults.inject("column_upload#0:raise@1"):
        got = turbo_svc.search(body)
    fails = got["_shards"].get("failures")
    assert fails and fails[0]["status"] == "recovered"
    assert fails[0]["reason"]["site"] == "column_upload"
    assert _hits(got) == _hits(want)
    assert serving_fault_stats()["shard_fault_recoveries"] > r0
    # clean retry: no failures reported, identical hits
    clean = turbo_svc.search(dict(body, size=11))
    assert "failures" not in clean["_shards"]
    assert clean["_shards"]["failed"] == 0


def test_timeout_yields_timed_out_partial(turbo_svc, monkeypatch):
    from elasticsearch_tpu.search.serving import serving_fault_stats

    monkeypatch.setenv("ES_TPU_COALESCE_US", "0")
    turbo_svc.search({"query": {"match": {"body": "alpha"}}})  # warm
    body = {"query": {"match": {"body": "alpha beta"}},
            "timeout": "5ms"}
    spec = ("turbo_sweep:hang=0.08;fused_dispatch:hang=0.08;"
            "column_upload:hang=0.08")
    with faults.inject(spec):
        resp = turbo_svc.search(body)
    assert resp["timed_out"] is True
    # no timeout -> same request completes normally
    resp2 = turbo_svc.search({"query": {"match": {"body": "alpha beta"}}})
    assert resp2["timed_out"] is False and resp2["hits"]["hits"]


def test_reject_errors_counted_and_logged_once(caplog):
    from elasticsearch_tpu.search import serving as sv

    class _BoomMapper:
        def __getattr__(self, name):
            raise RuntimeError("mapper exploded")

    n0 = sv.serving_fault_stats()["fastpath_reject_error"]
    with caplog.at_level(logging.WARNING, logger="search.serving"):
        for _ in range(3):
            assert sv.extract_plan({"query": {"match": {"body": "x"}}},
                                   _BoomMapper()) is None
    assert sv.serving_fault_stats()["fastpath_reject_error"] == n0 + 3
    hits = [r for r in caplog.records if "RuntimeError" in r.getMessage()]
    assert len(hits) == 1      # first occurrence logged, rest counted


def test_coalesced_turbo_fault_bit_identical():
    """Real engine through the coalescer under a one-shot fault: the
    merged dispatch contains the fault internally; rows stay identical
    to the solo host reference."""
    from elasticsearch_tpu.threadpool.coalescer import DispatchCoalescer

    eng = _engine([(700, _pcorpus(700, 40, 11))], mesh=False)
    eng.search_many([BATCH], k=K)              # warm columns
    co = DispatchCoalescer(window_us=200000)
    want = _host_many(eng, BATCH, K)
    with faults.inject("turbo_sweep:raise@1"):
        results, errors = _concurrent(co, eng, BATCH)
    assert errors == [None] * len(BATCH)
    for qi, r in enumerate(results):
        for j, name in enumerate(("scores", "parts", "ords")):
            assert np.array_equal(np.asarray(r[j][0]),
                                  np.asarray(want[j][qi])), (qi, name)

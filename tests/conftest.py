"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware is not available in CI; sharding correctness is tested on
a virtual CPU mesh exactly as the driver's dryrun does. Must run before jax
initializes its backends, hence env manipulation at import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware is not available in CI; sharding correctness is tested on
a virtual CPU mesh exactly as the driver's dryrun does.

The session's sitecustomize registers the axon TPU PJRT plugin in every
process and force-sets jax_platforms to "axon,cpu" via jax.config — so env
vars alone cannot keep tests off the (single, contended) TPU tunnel. We set
the config back to cpu here, before any backend is initialized (backends init
lazily at first use, which is after conftest import). Set TEST_ON_TPU=1 to
deliberately run the suite against the chip.
"""

import os

if os.environ.get("TEST_ON_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

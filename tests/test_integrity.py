"""End-to-end data integrity plane tests (PR 15).

Three legs, each differential where it counts:

  * at rest  — every committed segment blob carries a sha256 footer
               (v3 wire format); reads verify, corruption drops a
               corrupted-* marker and fails the COPY through the same
               shard-failed seam every other failure uses;
  * in flight — peer-recovery segment payloads ship with the source's
               pre-wire hash; the target verifies before install and
               re-fetches on mismatch (bounded, counted separately from
               node-unavailable retries);
  * in HBM   — engines register device-resident regions with host-side
               fingerprints; the scrubber detects injected bit flips and
               repairs from the host copy, and scrub-on vs scrub-off
               search results are bit-identical.

Cluster scenarios run on the synchronous CrashRestartCluster harness
(testing/chaos.py) — no sleeps, no polling.
"""

import glob
import os

import numpy as np
import pytest

from elasticsearch_tpu.common import faults, integrity
from elasticsearch_tpu.common.durability import reset_for_tests as _dur_reset
from elasticsearch_tpu.common.faults import inject
from elasticsearch_tpu.common.integrity import SegmentCorruptedError
from elasticsearch_tpu.index.engine import InternalEngine
from elasticsearch_tpu.index.segment_io import (
    MAGIC, MAGIC_V2, blob_hash, segment_from_blob, verify_blob,
)
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.testing.chaos import CrashRestartCluster

MAPPINGS = {"properties": {"n": {"type": "integer"},
                           "body": {"type": "text"}}}


@pytest.fixture(autouse=True)
def _clean():
    integrity.reset_for_tests()
    integrity.reset_scrub_for_tests()
    _dur_reset()
    yield
    faults.clear()
    integrity.reset_for_tests()
    integrity.reset_scrub_for_tests()
    _dur_reset()


def make_engine(path=None):
    return InternalEngine(MapperService(dict(MAPPINGS)), data_path=path)


def make_cluster(tmp_path, n_data=2, shards=1, replicas=1, index="docs"):
    names = ["m0"] + [f"d{i}" for i in range(n_data)]
    cluster = CrashRestartCluster(names, str(tmp_path),
                                  roles={"m0": ("master",)})
    cluster.master().create_index(index, {
        "settings": {"number_of_shards": shards,
                     "number_of_replicas": replicas},
        "mappings": MAPPINGS})
    return cluster


def write_op(doc_id, value):
    return {"op": "index", "id": doc_id,
            "source": {"n": value, "body": f"v{value}"}}


def node_of_copy(cluster, index, sid, primary):
    for r in cluster.store.current().shard_copies(index, sid):
        if r.primary == primary and r.node_id is not None \
                and r.state == "STARTED":
            return r.node_id
    return None


def shard_disk_segments(tmp_path, node_name, index="docs", sid=0):
    return sorted(glob.glob(os.path.join(
        str(tmp_path), node_name, index, str(sid), "segments", "*.seg")))


def corrupt_file(path):
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(integrity.bitflip(data))


# ------------------------------------------------- leg 1: at rest


def test_blob_footer_roundtrip_and_legacy_compat():
    """v3 blobs verify end-to-end; bit flips and truncation raise; v2
    blobs (no footer) stay readable and are counted, not rejected."""
    e = make_engine()
    for i in range(8):
        e.index(str(i), {"n": i, "body": f"doc {i} hello"})
    e.refresh()
    payloads, _ = e.segment_payloads()
    blob = payloads[0][0]
    assert blob.startswith(MAGIC)
    verify_blob(blob)                      # clean: no raise
    seg = segment_from_blob(blob)
    assert seg.n_docs == 8
    assert len(blob_hash(blob)) == 64

    with pytest.raises(SegmentCorruptedError):
        verify_blob(integrity.bitflip(blob))
    with pytest.raises(SegmentCorruptedError):
        segment_from_blob(integrity.bitflip(blob))
    with pytest.raises(SegmentCorruptedError):
        verify_blob(blob[:-10])            # truncated footer
    with pytest.raises(SegmentCorruptedError):
        verify_blob(b"NOTASEG" + blob)     # bad magic

    # a v2 blob is exactly the v3 body under the old magic, no footer
    legacy = MAGIC_V2 + blob[len(MAGIC):-32]
    seg2 = segment_from_blob(legacy)
    assert seg2.n_docs == 8
    stats = integrity.integrity_stats()
    assert stats["legacy_blobs_read"] == 1
    assert stats["segments_corrupted"] >= 3
    assert stats["segments_verified"] >= 2
    assert stats["bytes_verified"] > 0


def test_commit_load_verifies_and_writes_marker(tmp_path):
    """A bit flip in a committed segment fails the reload and drops a
    corrupted-* marker in the shard data path."""
    path = str(tmp_path / "shard")
    e = make_engine(path)
    for i in range(10):
        e.index(str(i), {"n": i, "body": f"doc {i}"})
    e.flush()
    make_engine(path)                      # clean reload verifies
    assert integrity.integrity_stats()["segments_verified"] >= 1

    corrupt_file(glob.glob(os.path.join(path, "segments", "*.seg"))[0])
    with pytest.raises(SegmentCorruptedError):
        make_engine(path)
    marker = integrity.corruption_marker(path)
    assert marker is not None and marker["segment"]
    assert integrity.integrity_stats()["markers_written"] == 1
    assert integrity.clear_corruption_markers(path) == 1
    assert integrity.corruption_marker(path) is None


def test_verify_store_catches_rot_under_loaded_engine(tmp_path):
    """The differential CHECK_ON_STARTUP buys: an engine that loaded
    cleanly keeps serving from memory after on-disk rot — verify_store
    (the startup scan) re-reads the store and catches it."""
    path = str(tmp_path / "shard")
    e = make_engine(path)
    for i in range(6):
        e.index(str(i), {"n": i, "body": f"doc {i}"})
    e.flush()
    e2 = make_engine(path)
    assert e2.verify_store() >= 1          # clean scan
    corrupt_file(glob.glob(os.path.join(path, "segments", "*.seg"))[0])
    assert e2.get("3") is not None         # still serves from memory
    with pytest.raises(SegmentCorruptedError):
        e2.verify_store()
    assert integrity.corruption_marker(path) is not None


def test_corrupt_primary_store_fails_copy_and_reallocates(tmp_path):
    """Acceptance: corrupt-on-disk -> shard failed + reallocated from the
    replica; the corrupted copy is quarantined and re-recovers from the
    healthy peer; every doc stays readable."""
    cluster = make_cluster(tmp_path, n_data=2)
    docs = [f"doc{i}" for i in range(12)]
    cluster.master().bulk("docs", [write_op(d, 1) for d in docs])
    victim = node_of_copy(cluster, "docs", 0, primary=True)
    survivor = node_of_copy(cluster, "docs", 0, primary=False)
    cluster.primary_instance("docs", docs[0]).engine.flush()

    # report=False: the master still believes the primary is STARTED on
    # the victim — the corruption is discovered by the restarted node
    # itself at commit load, not by failure detection
    cluster.crash(victim, report=False)
    segs = shard_disk_segments(tmp_path, victim)
    assert segs
    corrupt_file(segs[0])
    cluster.restart(victim)

    stats = integrity.integrity_stats()
    assert stats["segments_corrupted"] >= 1
    assert stats["markers_written"] >= 1
    assert stats["shards_failed_corrupt"] >= 1
    # the master moved the primary to the healthy peer
    assert node_of_copy(cluster, "docs", 0, primary=True) == survivor
    # the corrupt store was moved aside and rebuilt via peer recovery
    assert stats["copies_quarantined"] >= 1
    assert os.path.isdir(os.path.join(str(tmp_path), victim, "docs",
                                      "0.corrupt"))
    for d in docs:
        assert cluster.read_doc("docs", d)["n"] == 1
    # the rebuilt replica is tracked in-sync again
    inst = cluster.primary_instance("docs", docs[0])
    assert len(inst.tracker.in_sync_ids) == 2
    # and the fresh store carries no marker anymore
    assert integrity.corruption_marker(os.path.join(
        str(tmp_path), victim, "docs", "0")) is None


def test_marker_alone_blocks_primary_reassignment(tmp_path):
    """A corrupted-* marker must block the store from serving as primary
    even when the underlying files read back clean — the marker IS the
    tombstone, not the bit flip."""
    cluster = make_cluster(tmp_path, n_data=2)
    docs = [f"doc{i}" for i in range(6)]
    cluster.master().bulk("docs", [write_op(d, 2) for d in docs])
    victim = node_of_copy(cluster, "docs", 0, primary=True)
    survivor = node_of_copy(cluster, "docs", 0, primary=False)
    cluster.primary_instance("docs", docs[0]).engine.flush()
    cluster.crash(victim, report=False)
    # clean files + a marker: a previous incarnation found corruption
    integrity.write_corruption_marker(
        os.path.join(str(tmp_path), victim, "docs", "0"),
        "injected for test")
    cluster.restart(victim)
    assert integrity.integrity_stats()["shards_failed_corrupt"] >= 1
    assert node_of_copy(cluster, "docs", 0, primary=True) == survivor
    for d in docs:
        assert cluster.read_doc("docs", d)["n"] == 2


# ------------------------------------------------- leg 2: in flight


def test_transfer_corruption_retries_then_succeeds(tmp_path):
    """One injected wire corruption during peer recovery: the target's
    hash check catches it, the re-fetch is clean, the copy comes up
    in-sync — counted under transfer_*, not the node-unavailable loop."""
    cluster = make_cluster(tmp_path, n_data=3)
    docs = [f"doc{i}" for i in range(10)]
    cluster.master().bulk("docs", [write_op(d, 3) for d in docs])
    replica_holder = node_of_copy(cluster, "docs", 0, primary=False)
    with inject("segment_transfer:raise@1x1"):
        # the crash triggers reallocation + recovery to the spare node
        # synchronously; the first segment fetch arrives corrupted
        cluster.crash(replica_holder)
    stats = integrity.integrity_stats()
    assert stats["transfer_corruptions"] == 1
    assert stats["transfer_retries"] == 1
    assert stats["transfer_hashes_verified"] >= 1
    inst = cluster.primary_instance("docs", docs[0])
    assert len(inst.tracker.in_sync_ids) == 2
    for d in docs:
        assert cluster.read_doc("docs", d)["n"] == 3


def test_transfer_corruption_exhausts_retries_and_fails(tmp_path,
                                                        monkeypatch):
    """Persistent wire corruption: the bounded re-fetch loop gives up with
    SegmentCorruptedError instead of installing a damaged segment."""
    monkeypatch.setenv("ES_TPU_RECOVERY_RETRIES", "2")
    cluster = make_cluster(tmp_path, n_data=2)
    docs = [f"doc{i}" for i in range(5)]
    cluster.master().bulk("docs", [write_op(d, 4) for d in docs])
    primary_holder = node_of_copy(cluster, "docs", 0, primary=True)
    target = node_of_copy(cluster, "docs", 0, primary=False)
    svc = cluster.node(target).shard_service
    with inject("segment_transfer:raise@1x99"):
        with pytest.raises(SegmentCorruptedError):
            svc._fetch_verified_segments(
                primary_holder, {"index": "docs", "shard_id": 0})
    stats = integrity.integrity_stats()
    assert stats["transfer_corruptions"] == 3      # initial + 2 retries
    assert stats["transfer_retries"] == 2


# ------------------------------------------------- leg 3: in HBM


class _Seg:
    def __init__(self, n_docs, fp):
        self.n_docs = n_docs
        self.postings = {"body": fp}
        self.vectors = {}


def _corpus(n_docs=1500, vocab=120, seed=7):
    from elasticsearch_tpu.index.segment import build_field_postings

    rng = np.random.default_rng(seed)
    probs = 1.0 / np.arange(1, vocab + 1) ** 1.1
    probs /= probs.sum()
    lens = rng.integers(4, 20, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum()),
                        p=probs).astype(np.int64)
    tok_docs = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    fp = build_field_postings("body", lens, tok_docs, tokens,
                              [f"t{i}" for i in range(vocab)])
    return fp, n_docs


def _make_turbo():
    from elasticsearch_tpu.parallel.spmd import build_stacked_bm25
    from elasticsearch_tpu.parallel.turbo import TurboBM25

    fp, n_docs = _corpus()
    stacked = build_stacked_bm25([_Seg(n_docs, fp)], "body",
                                 serve_only=True)
    return TurboBM25(stacked, hbm_budget_bytes=64 << 20, cold_df=10)


def _scrub_full_cycle():
    out = []
    for _ in range(integrity.scrub_registry_size()):
        out.append(integrity.scrub_once())
    return out


def test_hbm_scrub_detects_and_repairs_injected_flip():
    """Acceptance: an injected hbm_region flip on a host-backed region is
    detected by the scrubber and repaired bit-identically from the host
    fingerprint copy; scrub-on vs scrub-off results are identical."""
    control = _make_turbo()
    integrity.reset_scrub_for_tests()      # only the scrubbed engine below
    turbo = _make_turbo()
    assert integrity.scrub_registry_size() >= 5

    queries = [[("t1", 1.0), ("t3", 1.0)], [("t2", 2.0)],
               [("t5", 1.0), ("t9", 1.0), ("t1", 1.0)]]
    want_s, want_d = control.search(queries, k=10)

    _scrub_full_cycle()                    # baseline pass: all clean
    st = integrity.integrity_stats()
    assert st["scrub_mismatches"] == 0 and st["scrub_clean"] >= 3

    with inject("hbm_region#lane_docs:raise@1x1"):
        results = _scrub_full_cycle()
    hit = [r for r in results if r and r["result"] == "mismatch"]
    assert len(hit) == 1 and hit[0]["region"].endswith(".lane_docs")
    st = integrity.integrity_stats()
    assert st["scrub_mismatches"] == 1
    assert st["scrub_repairs"] == 1
    assert st["scrub_repaired_bytes"] > 0

    # the repaired engine answers bit-identically to the never-scrubbed one
    got_s, got_d = turbo.search(queries, k=10)
    assert np.array_equal(np.asarray(want_d), np.asarray(got_d))
    assert np.array_equal(np.asarray(want_s), np.asarray(got_s))
    # and the next full cycle is clean again
    _scrub_full_cycle()
    assert integrity.integrity_stats()["scrub_mismatches"] == 1


def test_hbm_scrub_repairs_real_device_corruption():
    """No injection: overwrite the device-resident live mask with flipped
    bits directly — the scrubber restores it from the host copy."""
    import jax.numpy as jnp

    turbo = _make_turbo()
    good = np.asarray(turbo.live).copy()
    bad = np.frombuffer(
        integrity.bitflip(good.tobytes()), good.dtype).reshape(good.shape)
    turbo.live = jnp.asarray(bad)
    for _ in range(integrity.scrub_registry_size() * 2):
        integrity.scrub_once()
    assert integrity.integrity_stats()["scrub_repairs"] >= 1
    assert np.array_equal(np.asarray(turbo.live), good)


def test_scrub_baseline_regions_track_legitimate_updates():
    """Baseline (epoch) regions: a legitimate functional rebuild rebinds
    the array -> new epoch -> re-baseline, NOT a mismatch."""
    turbo = _make_turbo()
    # warm the column cache so cols_hi holds data, then scrub twice
    turbo.search([[("t1", 1.0)]], k=5)
    for _ in range(integrity.scrub_registry_size() * 2):
        integrity.scrub_once()
    before = integrity.integrity_stats()["scrub_mismatches"]
    # more searches may admit new columns (rebinding cols_hi/cols_lo)
    turbo.search([[("t2", 1.0), ("t4", 1.0)]], k=5)
    for _ in range(integrity.scrub_registry_size() * 2):
        integrity.scrub_once()
    st = integrity.integrity_stats()
    assert st["scrub_mismatches"] == before      # no false positives
    assert st["scrub_baselined"] >= 1


def test_scrub_region_registration_validation():
    class Owner:
        pass

    o = Owner()
    with pytest.raises(ValueError):
        integrity.register_scrub_region(o, "r", lambda x: None)
    with pytest.raises(ValueError):
        integrity.register_scrub_region(o, "r", lambda x: None,
                                        expected=lambda x: None,
                                        epoch=lambda x: 1)


def test_scrubber_lifecycle_and_overload_yield(monkeypatch):
    """start() is a no-op with the knob at 0; a non-GREEN overload level
    skips the tick (counted) without touching any region."""
    from elasticsearch_tpu.common.integrity import IntegrityScrubber

    assert IntegrityScrubber().start() is False   # knob defaults to 0

    class _Overload:
        def __init__(self, level):
            self._level = level

        def stats(self):
            return {"level": self._level}

    s = IntegrityScrubber(overload=_Overload("red"))
    s.tick()
    assert integrity.integrity_stats()["scrub_yields"] == 1
    s2 = IntegrityScrubber(overload=_Overload("green"))
    s2.tick()                                     # empty registry: no-op
    assert integrity.integrity_stats()["scrub_ticks"] == 0
    assert integrity.scrub_once() is None         # nothing registered

    monkeypatch.setenv("ES_TPU_INTEGRITY_SCRUB_S", "30")
    s3 = IntegrityScrubber()
    assert s3.start() is True
    s3.stop()


# ------------------------------------------------- startup checks


def test_check_on_startup_catches_corruption_before_started(tmp_path,
                                                            monkeypatch):
    """Acceptance: with ES_TPU_CHECK_ON_STARTUP the post-recovery store
    scan catches a segment_read corruption BEFORE the copy reports
    started; the master re-runs recovery and the copy lands healthy."""
    monkeypatch.setenv("ES_TPU_CHECK_ON_STARTUP", "1")
    cluster = make_cluster(tmp_path, n_data=3)
    docs = [f"doc{i}" for i in range(8)]
    cluster.master().bulk("docs", [write_op(d, 5) for d in docs])
    replica_holder = node_of_copy(cluster, "docs", 0, primary=False)
    with inject("segment_read:raise@1x1"):
        # reallocation + recovery to the spare node runs synchronously;
        # the startup scan's first blob read comes back flipped
        cluster.crash(replica_holder)
    stats = integrity.integrity_stats()
    assert stats["startup_checks"] >= 1
    assert stats["startup_failures"] == 1
    assert stats["shards_failed_corrupt"] >= 1
    # the retried recovery (injection exhausted) brought the copy up
    inst = cluster.primary_instance("docs", docs[0])
    assert len(inst.tracker.in_sync_ids) == 2
    for d in docs:
        assert cluster.read_doc("docs", d)["n"] == 5


def test_check_on_startup_off_skips_scan(tmp_path, monkeypatch):
    """Differential: with the knob OFF the same injection is never
    consulted — no scan, no failure, the copy starts immediately."""
    monkeypatch.delenv("ES_TPU_CHECK_ON_STARTUP", raising=False)
    cluster = make_cluster(tmp_path, n_data=3)
    docs = [f"doc{i}" for i in range(8)]
    cluster.master().bulk("docs", [write_op(d, 6) for d in docs])
    replica_holder = node_of_copy(cluster, "docs", 0, primary=False)
    with inject("segment_read:raise@1x1"):
        cluster.crash(replica_holder)
    stats = integrity.integrity_stats()
    assert stats["startup_checks"] == 0
    assert stats["startup_failures"] == 0
    inst = cluster.primary_instance("docs", docs[0])
    assert len(inst.tracker.in_sync_ids) == 2


# ------------------------------------------------- surfaces


def test_integrity_stats_section_shape():
    from elasticsearch_tpu.rest.handlers import _tpu_integrity_stats

    out = _tpu_integrity_stats()
    for key in ("segments_verified", "segments_corrupted",
                "markers_written", "shards_failed_corrupt",
                "copies_quarantined", "transfer_corruptions",
                "transfer_retries", "scrub_ticks", "scrub_mismatches",
                "scrub_repairs", "scrub_yields", "repo_verifies",
                "repo_corrupt_blobs", "restore_cleanups",
                "scrub_regions"):
        assert key in out, key


def test_corruption_fault_sites_registered():
    from elasticsearch_tpu.common.faults import (
        CORRUPTION_SITES, KNOWN_SITES, parse_spec,
    )

    assert CORRUPTION_SITES <= KNOWN_SITES
    for site in ("segment_read", "segment_transfer", "hbm_region"):
        clause = parse_spec(f"{site}#p1:raise@1")[0]
        assert clause.part == "p1"

"""Nested field mapping + nested query + inner_hits (VERDICT r2 next #9).

The flattened-object trap is the canonical test: with `object` arrays,
cross-object field combinations falsely match; with `nested`, a query must
match WITHIN one child object.
"""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService

MAPPINGS = {"properties": {
    "title": {"type": "text"},
    "comments": {
        "type": "nested",
        "properties": {
            "author": {"type": "keyword"},
            "text": {"type": "text"},
            "stars": {"type": "integer"},
        }},
}}


@pytest.fixture(scope="module")
def svc():
    meta = IndexMetadata(index="n", uuid="u", settings=Settings({}),
                         mappings=MAPPINGS)
    svc = IndexService(meta)
    svc.index_doc("1", {"title": "post one", "comments": [
        {"author": "kim", "text": "great stuff", "stars": 5},
        {"author": "lee", "text": "terrible take", "stars": 1},
    ]})
    svc.index_doc("2", {"title": "post two", "comments": [
        {"author": "kim", "text": "terrible take", "stars": 2},
    ]})
    svc.index_doc("3", {"title": "post three no comments"})
    svc.refresh()
    yield svc
    svc.close()


def test_nested_match_within_one_object(svc):
    """kim AND terrible must only match doc 2 (same child object) — the
    flattened-object semantics would wrongly match doc 1 too."""
    r = svc.search({"query": {"nested": {
        "path": "comments",
        "query": {"bool": {"must": [
            {"term": {"comments.author": "kim"}},
            {"match": {"comments.text": "terrible"}},
        ]}}}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["2"]


def test_nested_simple_match_and_score_modes(svc):
    base = {"path": "comments", "query": {"match": {"comments.text": "terrible"}}}
    r = svc.search({"query": {"nested": dict(base)}})
    assert {h["_id"] for h in r["hits"]["hits"]} == {"1", "2"}
    r_none = svc.search({"query": {"nested": {**base, "score_mode": "none"}}})
    assert all(h["_score"] == 0.0 for h in r_none["hits"]["hits"])  # ES: none -> 0
    # sum >= max >= avg for a parent with one matching child: all equal
    for mode in ("sum", "max", "min", "avg"):
        rm = svc.search({"query": {"nested": {**base, "score_mode": mode}}})
        assert {h["_id"] for h in rm["hits"]["hits"]} == {"1", "2"}


def test_nested_numeric_range_child(svc):
    r = svc.search({"query": {"nested": {
        "path": "comments",
        "query": {"range": {"comments.stars": {"gte": 5}}}}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]


def test_nested_in_bool_filter(svc):
    r = svc.search({"query": {"bool": {
        "must": [{"match": {"title": "post"}}],
        "filter": [{"nested": {
            "path": "comments",
            "query": {"term": {"comments.author": "lee"}}}}]}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]


def test_inner_hits(svc):
    r = svc.search({"query": {"nested": {
        "path": "comments",
        "query": {"match": {"comments.text": "terrible"}},
        "inner_hits": {}}}})
    by_id = {h["_id"]: h for h in r["hits"]["hits"]}
    ih1 = by_id["1"]["inner_hits"]["comments"]["hits"]
    assert ih1["total"]["value"] == 1
    assert ih1["hits"][0]["_source"]["author"] == "lee"
    assert ih1["hits"][0]["_nested"] == {"field": "comments", "offset": 1}
    ih2 = by_id["2"]["inner_hits"]["comments"]["hits"]
    assert ih2["hits"][0]["_source"]["author"] == "kim"


def test_nested_fields_not_searchable_at_parent_level(svc):
    """Child fields must not leak into parent-level postings."""
    r = svc.search({"query": {"match": {"comments.text": "terrible"}}})
    assert r["hits"]["hits"] == []


def test_nested_survives_segment_roundtrip(tmp_path):
    """Nested tables persist through flush/recovery (pickled segments)."""
    import pickle

    meta = IndexMetadata(index="np", uuid="u", settings=Settings({}),
                         mappings=MAPPINGS)
    svc = IndexService(meta)
    svc.index_doc("1", {"title": "x", "comments": [{"author": "a",
                                                    "text": "hello world"}]})
    svc.refresh()
    seg = svc.shards[0].acquire_searcher().views[0].segment
    seg2 = pickle.loads(pickle.dumps(seg))
    assert "comments" in seg2.nested
    assert seg2.nested["comments"].child.n_docs == 1
    svc.close()


def test_nested_max_mode_trailing_childless_parent():
    """Review r3 finding: the reduceat clamp truncated the LAST parent-with-
    children's run when trailing docs had no nested field."""
    meta = IndexMetadata(index="tc", uuid="u", settings=Settings({}),
                         mappings=MAPPINGS)
    svc = IndexService(meta)
    svc.index_doc("1", {"title": "x", "comments": [
        {"author": "a", "text": "meh", "stars": 1},
        {"author": "b", "text": "good match here", "stars": 9},
    ]})
    svc.index_doc("2", {"title": "no comments at all"})
    svc.refresh()
    r = svc.search({"query": {"nested": {
        "path": "comments", "score_mode": "max",
        "query": {"match": {"comments.text": "good match"}}}}})
    hits = r["hits"]["hits"]
    assert [h["_id"] for h in hits] == ["1"]
    import math

    assert math.isfinite(hits[0]["_score"]) and hits[0]["_score"] > 0
    r = svc.search({"query": {"nested": {
        "path": "comments", "score_mode": "min",
        "query": {"match": {"comments.text": "good match"}}}}})
    assert math.isfinite(r["hits"]["hits"][0]["_score"])
    svc.close()

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.mapper.field_types import MapperParsingError, parse_date_millis


MAPPING = {
    "properties": {
        "title": {"type": "text"},
        "tags": {"type": "keyword"},
        "views": {"type": "long"},
        "rating": {"type": "double"},
        "published": {"type": "date"},
        "active": {"type": "boolean"},
        "embedding": {"type": "dense_vector", "dims": 4},
        "author": {"properties": {"name": {"type": "keyword"}, "age": {"type": "integer"}}},
    }
}


def make_service():
    return MapperService(MAPPING)


def test_mapping_roundtrip():
    svc = make_service()
    m = svc.mapping()["properties"]
    assert m["title"]["type"] == "text"
    assert m["author"]["properties"]["name"]["type"] == "keyword"
    assert m["embedding"]["dims"] == 4


def test_parse_text_terms_and_lengths():
    svc = make_service()
    doc = svc.parse("1", {"title": "The quick brown fox the fox"})
    terms = dict(doc.inverted["title"])
    assert terms["fox"] == [3, 5]
    assert terms["the"] == [0, 4]
    assert doc.field_lengths["title"] == 6


def test_parse_multivalue_text_position_gap():
    svc = make_service()
    doc = svc.parse("1", {"title": ["foo bar", "baz"]})
    terms = dict(doc.inverted["title"])
    assert terms["foo"] == [0]
    assert terms["bar"] == [1]
    assert terms["baz"][0] >= 100  # position gap across values
    assert doc.field_lengths["title"] == 3  # gap does not inflate norm


def test_parse_numeric_date_bool_keyword_vector():
    svc = make_service()
    doc = svc.parse("1", {
        "views": 42,
        "rating": 4.5,
        "published": "2021-06-01T12:00:00Z",
        "active": True,
        "tags": ["a", "b"],
        "embedding": [1, 2, 3, 4],
        "author": {"name": "kimchy", "age": 40},
    })
    assert doc.numeric["views"] == [42.0]
    assert doc.numeric["rating"] == [4.5]
    assert doc.numeric["published"] == [float(parse_date_millis("2021-06-01T12:00:00Z"))]
    assert doc.numeric["active"] == [1.0]
    assert doc.keyword["tags"] == ["a", "b"]
    assert doc.keyword["author.name"] == ["kimchy"]
    assert doc.numeric["author.age"] == [40.0]
    np.testing.assert_array_equal(doc.vectors["embedding"], np.array([1, 2, 3, 4], np.float32))


def test_numeric_range_validation():
    svc = MapperService({"properties": {"n": {"type": "byte"}}})
    with pytest.raises(MapperParsingError):
        svc.parse("1", {"n": 1000})


def test_vector_dims_validation():
    svc = make_service()
    with pytest.raises(MapperParsingError):
        svc.parse("1", {"embedding": [1, 2, 3]})


def test_dynamic_mapping():
    svc = MapperService()
    doc = svc.parse("1", {"name": "hello world", "count": 3, "score": 1.5,
                          "flag": False, "when": "2020-01-01"})
    assert svc.field_type("name").params["type"] == "text"
    assert svc.field_type("name.keyword").params["type"] == "keyword"
    assert svc.field_type("count").params["type"] == "long"
    assert svc.field_type("score").params["type"] == "float"
    assert svc.field_type("flag").params["type"] == "boolean"
    assert svc.field_type("when").params["type"] == "date"
    assert dict(doc.inverted["name"])["hello"] == [0]
    assert doc.keyword["name.keyword"] == ["hello world"]


def test_merge_conflict_rejected():
    svc = make_service()
    with pytest.raises(IllegalArgumentError):
        svc.merge({"properties": {"title": {"type": "keyword"}}})
    # adding a new field is fine
    svc.merge({"properties": {"body": {"type": "text"}}})
    assert svc.field_type("body") is not None


def test_date_parsing_formats():
    assert parse_date_millis(0) == 0
    assert parse_date_millis("1577836800000") == 1577836800000
    assert parse_date_millis("2020-01-01") == 1577836800000
    assert parse_date_millis("2020-01-01T00:00:00Z") == 1577836800000
    with pytest.raises(MapperParsingError):
        parse_date_millis("not-a-date")

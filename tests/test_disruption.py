"""Coordinator shard failover under transport faults (PR 6).

The disruption lane: injected `rpc_*` faults, organic kills/partitions, and
deadline expiry all exercise the SAME coordinator recovery paths — replica
retry with excluded-node tracking, node transport circuits, and partial
results with per-shard `_shards.failures` accounting.
"""

import time

import pytest

from elasticsearch_tpu.action.search_action import _COORD_COUNTERS
from elasticsearch_tpu.cluster_node import form_local_cluster
from elasticsearch_tpu.common import faults
from elasticsearch_tpu.common.errors import SearchPhaseExecutionError
from elasticsearch_tpu.transport.channels import NodeUnavailableError

pytestmark = pytest.mark.disruption

MAPPINGS = {"properties": {"n": {"type": "integer"},
                           "body": {"type": "text"}}}


def make_cluster(n_data=3, data_path=None):
    names = ["m0"] + [f"d{i}" for i in range(n_data)]
    roles = {"m0": ("master",)}
    return form_local_cluster(names, data_path=data_path, roles=roles)


def index_body(shards=2, replicas=1):
    return {"settings": {"number_of_shards": shards,
                         "number_of_replicas": replicas},
            "mappings": MAPPINGS}


def bulk_ops(start, count):
    return [{"op": "index", "id": str(i),
             "source": {"n": i, "body": f"word{i % 7} common text"}}
            for i in range(start, start + count)]


def snap():
    return dict(_COORD_COUNTERS)


def delta(before, key):
    return _COORD_COUNTERS[key] - before[key]


def ranked_first(coordinator, store, index="docs", sid=0):
    """The copy holder the coordinator would query first for this shard."""
    copies = [r for r in store.current().shard_copies(index, sid)
              if r.state == "STARTED"]
    return coordinator.search_action._rank_copies(copies)[0]


def normalized(resp):
    out = dict(resp)
    out.pop("took", None)
    return out


BODY = {"query": {"match": {"body": "common"}}, "size": 10,
        "track_total_hits": True}


def test_injected_rpc_fault_fails_over_bit_identical():
    """The acceptance differential: with one node's query RPC faulted and a
    second STARTED copy available, the response is bit-identical to the
    fault-free run, `_shards.failed == 0`, and `shard_retries > 0`."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    victim = ranked_first(master, store)
    before = snap()
    with faults.inject(f"rpc_query#{victim}:raisexinf"):
        r_fault = master.search("docs", BODY)
    assert r_fault["_shards"]["failed"] == 0
    assert "failures" not in r_fault["_shards"]
    assert delta(before, "shard_retries") >= 1

    r_clean = master.search("docs", BODY)
    assert normalized(r_fault) == normalized(r_clean)


def test_organic_kill_fails_over_and_revives():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    victim = ranked_first(master, store)
    channels.kill(victim)
    r = master.search("docs", BODY)
    assert r["_shards"]["failed"] == 0
    assert r["hits"]["total"]["value"] == 40

    channels.revive(victim)
    r2 = master.search("docs", BODY)
    assert r2["_shards"]["failed"] == 0
    assert normalized(r) == normalized(r2)


def test_partition_and_heal():
    """A one-sided partition (coordinator cut off from one data node) is
    routed around via replicas; heal restores the direct path."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    victim = ranked_first(master, store)
    channels.partition({"m0"}, {victim})
    r = master.search("docs", BODY)
    assert r["_shards"]["failed"] == 0
    assert r["hits"]["total"]["value"] == 40

    channels.heal()
    r2 = master.search("docs", BODY)
    assert normalized(r) == normalized(r2)


def test_all_copies_down_partial_results():
    """Every copy of every shard faulted: the response is a PARTIAL with a
    populated `_shards.failures` array — not an exception."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    with faults.inject("rpc_query:raisexinf"):
        r = master.search("docs", BODY)
    assert r["_shards"]["failed"] == r["_shards"]["total"] == 2
    assert r["_shards"]["successful"] == 0
    assert r["hits"]["hits"] == []
    failures = r["_shards"]["failures"]
    assert len(failures) == 2
    for f in failures:
        assert f["reason"]["type"] == "node_not_connected_exception"
        assert f["reason"]["phase"] == "query"
        # excluded-node tracking: every copy was attempted before giving up
        assert len(f["reason"]["attempted_nodes"]) == 2


def test_all_copies_down_strict_raises():
    """allow_partial_search_results=false escalates exhausted shards to a
    search_phase_execution_exception instead of a partial."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    body = dict(BODY, allow_partial_search_results=False)
    with faults.inject("rpc_query:raisexinf"):
        with pytest.raises(SearchPhaseExecutionError) as ei:
            master.search("docs", body)
    assert ei.value.error_type == "search_phase_execution_exception"
    assert ei.value.metadata["failures"]
    # reader contexts must not leak out of the failed request
    for n in nodes:
        assert n.search_action.contexts.open_contexts == 0


def test_hung_node_deadline_yields_timed_out_partial():
    """A hung query RPC is abandoned when the request timeout expires; the
    response comes back `timed_out: true` within the budget."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    before = snap()
    body = dict(BODY, timeout="150ms")
    t0 = time.monotonic()
    with faults.inject("rpc_query:hangxinf=0.5"):
        r = master.search("docs", body)
    assert time.monotonic() - t0 < 2.0
    assert r["timed_out"] is True
    assert r["_shards"]["failed"] >= 1
    assert delta(before, "rpc_timeouts") >= 1
    assert any(f["reason"]["type"] == "receive_timeout_transport_exception"
               for f in r["_shards"]["failures"])
    time.sleep(0.6)   # drain the abandoned hang threads before teardown


def test_rpc_timeout_floor_fails_over_to_replica(monkeypatch):
    """With no request timeout, ES_TPU_RPC_TIMEOUT_MS alone bounds each RPC:
    a hung node times out and the shard recovers on its replica — full
    results, no timed_out flag, bit-identical to the fault-free run."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    # warm the query path first: cold-start compilation must not read as a
    # hung node once the floor applies
    clean = master.search("docs", BODY)

    monkeypatch.setenv("ES_TPU_RPC_TIMEOUT_MS", "400")
    victim = ranked_first(master, store)
    before = snap()
    with faults.inject(f"rpc_query#{victim}:hangxinf=2.0"):
        r = master.search("docs", BODY)
    assert r["_shards"]["failed"] == 0
    assert r["timed_out"] is False
    assert r["hits"]["total"]["value"] == 40
    assert delta(before, "rpc_timeouts") >= 1
    assert delta(before, "shard_retries") >= 1
    assert normalized(r) == normalized(clean)
    time.sleep(1.8)   # drain the abandoned hang threads before teardown


def test_transport_circuit_opens_then_recovers(monkeypatch):
    """Consecutive transport failures to one node open its circuit (routing
    quarantine); after the backoff a half-open probe against the revived
    node closes it again."""
    monkeypatch.setenv("ES_TPU_HEALTH_BACKOFF_MS", "50")
    nodes, store, channels = make_cluster(n_data=2)
    master, a, b = nodes
    a.create_index("docs", index_body(2, 0))
    a.bulk("docs", bulk_ops(0, 30))
    a.refresh("docs")

    victim = ranked_first(master, store)
    channels.kill(victim)
    svc = master.search_action
    for _ in range(4):
        r = master.search("docs", BODY)
        assert r["_shards"]["failed"] >= 1   # single-copy shard is down
        if (h := svc._node_health.get(victim)) and h.state == "open":
            break
    h = svc._node_health.get(victim)
    assert h is not None and h.state == "open"

    # quarantined-but-only-copy: the next search still force-probes it
    before = snap()
    master.search("docs", BODY)
    assert delta(before, "node_circuit_open") >= 1

    channels.revive(victim)
    time.sleep(0.07)   # past the 50ms backoff -> half-open probe admitted
    r = master.search("docs", BODY)
    assert r["_shards"]["failed"] == 0
    assert h.state == "closed"


def test_can_match_failopen_reroutes_to_replica():
    """A can_match fault fails OPEN (shard kept) and demotes the
    unreachable node so the query phase targets the replica directly."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(4, 1))
    a.bulk("docs", [{"op": "index", "id": "special",
                     "source": {"n": 1, "body": "uniqueterm only here"}}]
           + bulk_ops(0, 40))
    a.refresh("docs")

    victim = ranked_first(master, store)
    before = snap()
    body = {"query": {"term": {"body": "uniqueterm"}},
            "track_total_hits": True}
    with faults.inject(f"rpc_can_match#{victim}:raisexinf"):
        r = master.search("docs", body)
    assert r["hits"]["total"]["value"] == 1
    assert r["_shards"]["failed"] == 0
    # ES semantics: `successful` counts skipped shards too
    assert r["_shards"]["successful"] == r["_shards"]["total"]
    assert delta(before, "can_match_reroutes") >= 1


def test_fetch_failure_drops_one_shard_keeps_rest():
    """A failed fetch drops THAT shard's hits — with a phase:fetch failure
    entry — while other shards' hits and every reader context survive."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    body = dict(BODY, size=20)
    clean = master.search("docs", body)
    assert len(clean["hits"]["hits"]) == 20

    # the fetch goes to whichever node SERVED the query; fault them all
    before = snap()
    with faults.inject("rpc_fetch:raisexinf"):
        r = master.search("docs", body)
    assert r["hits"]["total"]["value"] == 40    # query phase succeeded
    assert r["hits"]["hits"] == []              # every fetch dropped
    assert r["_shards"]["failed"] == 2
    assert r["_shards"]["successful"] == 0
    assert all(f["reason"]["phase"] == "fetch"
               for f in r["_shards"]["failures"])
    assert delta(before, "fetch_failures") == 2
    # the leak fix: contexts freed even though the fetch never ran
    for n in nodes:
        assert n.search_action.contexts.open_contexts == 0

    # single-node fault: the OTHER shard's hits survive
    served_nodes = {ranked_first(master, store, sid=s) for s in range(2)}
    if len(served_nodes) == 2:
        victim = sorted(served_nodes)[0]
        with faults.inject(f"rpc_fetch#{victim}:raisexinf"):
            r2 = master.search("docs", body)
        assert r2["_shards"]["failed"] == 1
        assert 0 < len(r2["hits"]["hits"]) < 20


def test_deadline_expired_mid_fanout_skips_remaining_shards():
    """When the budget dies between shards, un-attempted shards become
    timed-out failures rather than hanging the request."""
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(3, 1))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")

    before = snap()
    # every copy of every shard hangs 120ms; 200ms budget covers ~1 shard
    with faults.inject("rpc_query:hangxinf=0.12"):
        r = master.search("docs", dict(BODY, timeout="200ms"))
    assert r["timed_out"] is True
    assert delta(before, "rpc_timeouts") + delta(
        before, "deadline_expired") >= 1
    assert r["_shards"]["failed"] + r["_shards"]["successful"] \
        == r["_shards"]["total"]
    time.sleep(0.3)   # drain the abandoned hang threads before teardown


def test_coordinator_stats_section():
    """GET /_nodes/stats exposes the resilience counters + circuits under
    `tpu_coordinator`."""
    from elasticsearch_tpu.rest.handlers import _tpu_coordinator_stats

    s = _tpu_coordinator_stats()
    for key in ("shard_retries", "node_circuit_open", "rpc_timeouts",
                "fetch_failures", "can_match_reroutes", "deadline_expired"):
        assert isinstance(s[key], int)
    assert "open_circuits" in s["transport"]
    assert "transport_failures" in s["transport"]


def test_disruptable_transport_error_taxonomy():
    """DisruptableMockTransport-style drops surface NodeUnavailableError to
    arg-accepting callbacks; legacy zero-arg callbacks still fire."""
    from elasticsearch_tpu.testing.deterministic import DeterministicTaskQueue
    from elasticsearch_tpu.testing.disruptable_transport import (
        DisruptableTransport,
    )

    q = DeterministicTaskQueue(seed=7)
    t = DisruptableTransport(q)
    t.register("a", lambda sender, msg, reply: reply({"ok": True}))

    errs, legacy, replies = [], [], []
    t.send("x", "missing", {"m": 1}, replies.append, errs.append)
    t.send("x", "missing", {"m": 2}, replies.append,
           lambda: legacy.append(1))
    q.run_until_quiet()
    assert len(errs) == 1 and isinstance(errs[0], NodeUnavailableError)
    assert "no route" in str(errs[0])
    assert legacy == [1]

    # a two-sided partition drops the request the same way
    t.register("b", lambda sender, msg, reply: reply({"ok": True}))
    t.partition({"a"}, {"b"})
    t.send("a", "b", {"m": 3}, replies.append, errs.append)
    q.run_until_quiet()
    assert len(errs) == 2 and isinstance(errs[1], NodeUnavailableError)
    t.heal()
    t.send("a", "b", {"m": 4}, replies.append, errs.append)
    q.run_until_quiet()
    assert replies and replies[-1] == {"ok": True}

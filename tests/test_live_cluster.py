"""The spine over REAL sockets: election, join, allocation, replicated
writes, distributed search, node-death failover — everything the
deterministic harness checks, but with serialization, real concurrency and
socket failure in the loop (VERDICT r2: the live path had zero coverage)."""

import time

import pytest

from elasticsearch_tpu.cluster_node import LiveClusterNode

MAPPINGS = {"properties": {"n": {"type": "integer"},
                           "body": {"type": "text"}}}


def start_cluster(tmp_path, names=("n0", "n1", "n2")):
    nodes = [LiveClusterNode(n, voting_config=list(names),
                             data_path=str(tmp_path / n),
                             ping_interval=0.3, ping_fail_limit=2)
             for n in names]
    for n in nodes:
        n.bind()
    seeds = [("127.0.0.1", n.bound_port) for n in nodes]
    for n in nodes:
        n.start(seeds)
    return nodes


def await_green(node, index, n_copies, timeout=30.0):
    def pred(st):
        copies = st.shard_copies(index, 0)
        all_copies = [r for shards in st.routing.values() for r in shards]
        return (len(all_copies) >= n_copies
                and all(r.state == "STARTED" for r in all_copies))

    return node.await_state(pred, timeout)


def test_live_three_node_cluster_end_to_end(tmp_path):
    nodes = start_cluster(tmp_path)
    try:
        # a leader emerges and every node joins with its address
        leader_name = nodes[0].formation.await_leader(30.0)
        any_node = nodes[0]
        any_node.await_state(
            lambda st: len(st.nodes) == 3
            and all(n.address for n in st.nodes.values()), 30.0)

        leader = next(n for n in nodes if n.node_name == leader_name)
        non_leader = next(n for n in nodes if n.node_name != leader_name)

        # create index via a NON-leader (master_client forwards)
        non_leader.create_index("docs", {
            "settings": {"number_of_shards": 2, "number_of_replicas": 1},
            "mappings": MAPPINGS})
        await_green(non_leader, "docs", 4)

        # bulk via one node
        writer = nodes[1]
        ops = [{"op": "index", "id": str(i),
                "source": {"n": i, "body": f"word{i % 5} common"}}
               for i in range(60)]
        resp = writer.bulk("docs", ops)
        assert not resp["errors"]
        writer.refresh("docs")

        # search via another node
        searcher = nodes[2]
        r = searcher.search("docs", {"query": {"match": {"body": "common"}},
                                     "size": 5, "track_total_hits": True})
        assert r["hits"]["total"]["value"] == 60
        assert r["_shards"]["failed"] == 0

        # kill the node holding shard 0's primary (never the leader, to keep
        # the master seat stable for this test's scope)
        st = searcher.state
        victim_name = st.primary_of("docs", 0).node_id
        if victim_name == leader_name:
            victim_name = st.primary_of("docs", 1).node_id
            victim_shard = 1
        else:
            victim_shard = 0
        if victim_name == leader_name:
            pytest.skip("both primaries landed on the leader")
        old_term = st.indices["docs"].primary_term(victim_shard)
        victim = next(n for n in nodes if n.node_name == victim_name)
        victim.stop()

        survivors = [n for n in nodes if n.node_name != victim_name]
        # fault detection removes the node; allocation promotes the replica
        survivors[0].await_state(
            lambda s: victim_name not in s.nodes
            and s.primary_of("docs", victim_shard) is not None
            and s.primary_of("docs", victim_shard).state == "STARTED"
            and s.primary_of("docs", victim_shard).node_id != victim_name,
            30.0)
        new_st = survivors[0].state
        assert new_st.indices["docs"].primary_term(victim_shard) \
            == old_term + 1

        # writes continue through the promoted primary
        ops2 = [{"op": "index", "id": f"post-{i}",
                 "source": {"n": 100 + i, "body": "after failover"}}
                for i in range(10)]
        resp2 = survivors[0].bulk("docs", ops2)
        assert not resp2["errors"]
        survivors[0].refresh("docs")
        r2 = survivors[1].search(
            "docs", {"query": {"match_all": {}},
                     "track_total_hits": True, "size": 0})
        assert r2["hits"]["total"]["value"] == 70
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:  # noqa: BLE001
                pass


def test_live_lifecycle_rollover_aliases_close(tmp_path):
    """Multi-node lifecycle (VERDICT r4 item 7): write-index alias rollover
    and open/close as cluster-state transitions, observed from EVERY node."""
    from elasticsearch_tpu.common.errors import (
        IndexClosedError, IndexNotFoundError,
    )

    nodes = start_cluster(tmp_path)
    try:
        nodes[0].formation.await_leader(30.0)
        nodes[0].await_state(lambda st: len(st.nodes) == 3, 30.0)

        nodes[1].create_index("logs-000001", {
            "settings": {"number_of_shards": 1, "number_of_replicas": 1},
            "mappings": MAPPINGS,
            "aliases": {"logs": {"is_write_index": True}}})
        await_green(nodes[1], "logs-000001", 2)

        # writes resolve the alias to the write index on any node
        writer = nodes[2]
        writer.await_state(lambda st: "logs-000001" in st.indices, 30.0)
        writer.bulk("logs", [{"op": "index", "id": "a",
                              "source": {"n": 1, "body": "first"}}])

        # rollover through a non-master-aware coordinator
        out = nodes[0].rollover("logs", {"conditions": {"max_docs": 1000}})
        assert out["rolled_over"] is False           # condition unmet
        out = nodes[0].rollover("logs")
        assert out["rolled_over"] is True
        assert out["new_index"] == "logs-000002"
        # every node observes the swapped alias
        for n in nodes:
            n.await_state(
                lambda st: "logs-000002" in st.indices
                and st.indices["logs-000002"].aliases
                .get("logs", {}).get("is_write_index") is True
                and st.indices["logs-000001"].aliases
                .get("logs", {}).get("is_write_index") is False, 40.0)
        await_green(nodes[0], "logs-000002", 4)

        # post-rollover writes land in the new index
        writer.bulk("logs", [{"op": "index", "id": "b",
                              "source": {"n": 2, "body": "second"}}])
        writer.refresh("logs-000001")
        writer.refresh("logs-000002")
        r1 = nodes[0].search("logs-000001", {"query": {"match_all": {}}})
        r2 = nodes[0].search("logs-000002", {"query": {"match_all": {}}})
        assert [h["_id"] for h in r1["hits"]["hits"]] == ["a"]
        assert [h["_id"] for h in r2["hits"]["hits"]] == ["b"]

        # close blocks search + bulk cluster-wide; open restores
        nodes[1].close_index("logs-000001")
        for n in nodes:
            n.await_state(
                lambda st: st.indices["logs-000001"].state == "close", 30.0)
        with pytest.raises(IndexClosedError):
            nodes[2].search("logs-000001", {"query": {"match_all": {}}})
        nodes[1].open_index("logs-000001")
        for n in nodes:
            n.await_state(
                lambda st: st.indices["logs-000001"].state == "open", 30.0)
        r = nodes[2].search("logs-000001", {"query": {"match_all": {}}})
        assert len(r["hits"]["hits"]) == 1
    finally:
        for n in nodes:
            n.stop()

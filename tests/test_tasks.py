"""Task registry, cooperative cancellation, timeouts, terminate_after
(VERDICT r2 next #6)."""

import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.tasks import TaskCancelledError, TaskManager


@pytest.fixture(scope="module")
def svc():
    meta = IndexMetadata(index="t", uuid="u", settings=Settings({}),
                         mappings={"properties": {
                             "body": {"type": "text"},
                             "n": {"type": "integer"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(3)
    for i in range(600):
        words = [f"w{rng.integers(0, 3000)}" for _ in range(6)]
        svc.index_doc(str(i), {"body": " ".join(words), "n": i})
        if i % 100 == 99:
            svc.refresh()       # several segments -> several check points
    svc.refresh()
    yield svc
    svc.close()


def test_task_register_list_cancel():
    tm = TaskManager("node-A")
    t = tm.register("indices:data/read/search", "test")
    assert tm.get(t.id) is t
    assert t in tm.list("indices:data/read/*")
    assert tm.list("cluster:*") == []
    tm.cancel(t.id)
    assert t.is_cancelled
    with pytest.raises(TaskCancelledError):
        t.check()
    tm.unregister(t)
    assert tm.get(t.id) is None


def test_precancelled_search_raises_promptly(svc):
    tm = TaskManager("n")
    task = tm.register("indices:data/read/search", "wildcard agg")
    task.cancel()
    body = {"query": {"wildcard": {"body": {"value": "w1*"}}},
            "aggs": {"m": {"max": {"field": "n"}}}}
    t0 = time.monotonic()
    with pytest.raises(TaskCancelledError):
        svc.search(body, task=task)
    assert time.monotonic() - t0 < 2.0


def test_cancel_mid_flight_returns_promptly(svc):
    """The VERDICT done-criterion: a deliberately heavy wildcard-agg query
    cancelled mid-flight returns promptly (checks fire between leaves and
    inside the expansion loop)."""
    tm = TaskManager("n")
    body = {"query": {"wildcard": {"body": {"value": "w*"}}},
            "aggs": {"terms": {"terms": {"field": "body.keyword" if False else "n",
                                         "size": 50}}}}
    # uncancelled baseline
    t0 = time.monotonic()
    svc._search_dense(body)
    full_wall = time.monotonic() - t0

    task = tm.register("indices:data/read/search", "heavy")
    canceller = threading.Timer(min(full_wall / 4, 0.05), task.cancel)
    canceller.start()
    t0 = time.monotonic()
    try:
        svc.search(body, task=task)
        # cancellation may lose the race on a fast machine; only assert
        # promptness when it won
    except TaskCancelledError:
        wall = time.monotonic() - t0
        assert wall < full_wall + 0.5
    finally:
        canceller.cancel()


def test_timeout_returns_partial_with_timed_out_flag(svc):
    body = {"query": {"match_all": {}}, "timeout": "0ms",
            "track_total_hits": True}
    r = svc._search_dense(body)
    # 0ms deadline expires before the second leaf; partial results, flagged
    assert r["timed_out"] is True
    full = svc._search_dense({"query": {"match_all": {}},
                              "track_total_hits": True})
    assert full["timed_out"] is False
    assert r["hits"]["total"]["value"] <= full["hits"]["total"]["value"]


def test_terminate_after(svc):
    body = {"query": {"match_all": {}}, "terminate_after": 150,
            "track_total_hits": True}
    r = svc._search_dense(body)
    assert r.get("terminated_early") is True
    assert 150 <= r["hits"]["total"]["value"] < 600


def test_tasks_rest_api(svc):
    import json

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest import RestController, register_handlers

    node = Node()
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, params=None):
        raw = json.dumps(body).encode() if body is not None else None
        resp = rc.dispatch(method, path, params or {}, raw)
        return resp.status, json.loads(resp.encode() or b"{}")

    t = node.tasks.register("indices:data/read/search", "slow one")
    status, body = call("GET", "/_tasks")
    assert status == 200
    tasks = body["nodes"][node.tasks.node_id]["tasks"]
    assert f"{t.node}:{t.id}" in tasks
    status, body = call("GET", f"/_tasks/{t.node}:{t.id}")
    assert status == 200 and body["task"]["description"] == "slow one"
    status, body = call("POST", f"/_tasks/{t.node}:{t.id}/_cancel")
    assert status == 200 and t.is_cancelled
    status, _ = call("GET", "/_tasks/zzz:notanum")
    assert status == 400
    node.close()

"""tpulint lane (PR 7): rule fixtures, seeded regressions, and the
package-wide zero-findings gate.

Each rule gets a detection fixture, a clean twin, and a suppression
check; the seeded-regression tests then simulate exactly the rot each
rule exists to catch (deleting a fault_point, mutating guarded state
outside its lock, a typo'd knob) and assert the finding appears. The
meta-tests pin the baseline to reality: every entry must point at a line
that still exists AND still fire, and the package itself must lint clean.
"""

from __future__ import annotations

from pathlib import Path

import pytest

from tools.tpulint.core import (
    Finding, apply_baseline, lint_paths, lint_sources, load_baseline,
)

pytestmark = pytest.mark.lint

ROOT = Path(__file__).resolve().parents[1]
BASELINE = ROOT / "tools" / "tpulint" / "baseline.txt"


def rules_of(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------------------
# TPU001 — unguarded dispatch
# --------------------------------------------------------------------------

_SETTINGS_TWIN = (
    "elasticsearch_tpu/common/settings.py",
    '''
def declare_knob(name, type, default, doc):
    pass

declare_knob("ES_TPU_REAL", "int", 1, "a declared knob")
''',
)

_TPU001_PATH = "elasticsearch_tpu/parallel/fixture.py"

_TPU001_BAD = '''
import jax
from elasticsearch_tpu.common import faults

_prog = jax.jit(lambda x: x + 1)

def run(x):
    return _prog(x)
'''

_TPU001_CLEAN = '''
import jax
from elasticsearch_tpu.common import faults

_prog = jax.jit(lambda x: x + 1)

def run(x):
    with faults.device_errors("turbo_sweep", 0):
        return _prog(x)
'''

_TPU001_FAULT_POINT = '''
import jax
from elasticsearch_tpu.common import faults

_prog = jax.jit(lambda x: x + 1)

def run(x):
    faults.fault_point("turbo_sweep", 0)
    return _prog(x)
'''


def test_tpu001_detects_unguarded_dispatch():
    findings = lint_sources([(_TPU001_PATH, _TPU001_BAD)])
    assert rules_of(findings) == ["TPU001"]
    assert "_prog" in findings[0].message


def test_tpu001_clean_twin_passes():
    assert lint_sources([(_TPU001_PATH, _TPU001_CLEAN)]) == []
    assert lint_sources([(_TPU001_PATH, _TPU001_FAULT_POINT)]) == []


def test_tpu001_device_put_flagged_and_jit_def_is_not():
    src = '''
import jax

@jax.jit
def kernel(x):
    return x + 1          # trace-time body: never a dispatch site

def upload(a):
    return jax.device_put(a)
'''
    findings = lint_sources([(_TPU001_PATH, src)])
    assert rules_of(findings) == ["TPU001"]
    assert "device_put" in findings[0].message


def test_tpu001_suppression():
    src = _TPU001_BAD.replace(
        "return _prog(x)", "return _prog(x)  # tpulint: disable=TPU001")
    assert lint_sources([(_TPU001_PATH, src)]) == []


def test_tpu001_only_applies_to_dispatch_layers():
    # the same unguarded call in a non-dispatch layer is not flagged
    assert lint_sources([("elasticsearch_tpu/rest/fixture.py",
                          _TPU001_BAD)]) == []


def test_seeded_regression_deleting_fault_point_is_caught():
    # the ISSUE's canary: remove the fault_point wrapper from a guarded
    # dispatch site and the linter must notice
    broken = _TPU001_FAULT_POINT.replace(
        '    faults.fault_point("turbo_sweep", 0)\n', "")
    assert lint_sources([(_TPU001_PATH, _TPU001_FAULT_POINT)]) == []
    assert rules_of(lint_sources([(_TPU001_PATH, broken)])) == ["TPU001"]


# --------------------------------------------------------------------------
# TPU002 — guarded-by
# --------------------------------------------------------------------------

_TPU002_PATH = "elasticsearch_tpu/common/fixture.py"

_TPU002_CLEAN = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []       # guarded by: _lock
        self.count = 0         # guarded by: _lock

    def push(self, x):
        with self._lock:
            self._items.append(x)
            self.count += 1
'''

_TPU002_BAD = _TPU002_CLEAN + '''
    def rogue(self, x):
        self._items.append(x)
'''


def test_tpu002_detects_unlocked_mutation():
    findings = lint_sources([(_TPU002_PATH, _TPU002_BAD)])
    assert rules_of(findings) == ["TPU002"]
    assert "_items" in findings[0].message


def test_tpu002_clean_twin_passes():
    assert lint_sources([(_TPU002_PATH, _TPU002_CLEAN)]) == []


def test_tpu002_holds_marker_trusts_helper():
    src = _TPU002_CLEAN + '''
    def _push_locked(self, x):  # tpulint: holds=_lock
        self._items.append(x)
'''
    assert lint_sources([(_TPU002_PATH, src)]) == []


def test_tpu002_module_global_and_augassign():
    src = '''
import threading

_LOCK = threading.Lock()
_STATS = {"n": 0}   # guarded by: _LOCK

def good():
    with _LOCK:
        _STATS["n"] += 1

def bad():
    _STATS["n"] += 1
'''
    findings = lint_sources([(_TPU002_PATH, src)])
    assert rules_of(findings) == ["TPU002"]
    assert findings[0].line == src.splitlines().index('    _STATS["n"] += 1',
                                                      8) + 1


def test_tpu002_suppression():
    src = _TPU002_BAD.replace(
        "        self._items.append(x)\n" ,
        "        self._items.append(x)  # tpulint: disable=TPU002\n")
    assert lint_sources([(_TPU002_PATH, src)]) == []


def test_seeded_regression_guarded_mutation_outside_lock_is_caught():
    broken = _TPU002_CLEAN.replace(
        "        with self._lock:\n"
        "            self._items.append(x)\n"
        "            self.count += 1\n",
        "        self._items.append(x)\n"
        "        self.count += 1\n")
    findings = lint_sources([(_TPU002_PATH, broken)])
    assert rules_of(findings) == ["TPU002", "TPU002"]


# --------------------------------------------------------------------------
# TPU003 — knob registry
# --------------------------------------------------------------------------

_TPU003_PATH = "elasticsearch_tpu/common/fixture.py"


def test_tpu003_detects_direct_env_read():
    src = '''
import os
v = os.environ.get("ES_TPU_SECRET_KNOB", "")
w = os.environ["ES_TPU_OTHER"]
x = os.getenv("ES_TPU_THIRD")
'''
    findings = lint_sources([(_TPU003_PATH, src), _SETTINGS_TWIN])
    assert rules_of(findings) == ["TPU003", "TPU003", "TPU003"]


def test_tpu003_knob_call_and_non_es_tpu_env_are_clean():
    src = '''
import os
from elasticsearch_tpu.common.settings import knob

a = knob("ES_TPU_REAL")
b = os.environ.get("HOME")
'''
    assert lint_sources([(_TPU003_PATH, src), _SETTINGS_TWIN]) == []


def test_tpu003_fstring_env_read_flagged():
    src = '''
import os

def read(name):
    return os.environ.get(f"ES_TPU_POOL_{name}_SIZE")
'''
    findings = lint_sources([(_TPU003_PATH, src), _SETTINGS_TWIN])
    assert rules_of(findings) == ["TPU003"]


def test_tpu003_suppression():
    src = 'import os\nv = os.environ.get("ES_TPU_X")  # tpulint: disable=TPU003\n'
    assert lint_sources([(_TPU003_PATH, src), _SETTINGS_TWIN]) == []


def test_seeded_regression_undeclared_knob_is_caught():
    ok = 'from elasticsearch_tpu.common.settings import knob\nv = knob("ES_TPU_REAL")\n'
    typo = ok.replace("ES_TPU_REAL", "ES_TPU_RAEL")
    assert lint_sources([(_TPU003_PATH, ok), _SETTINGS_TWIN]) == []
    findings = lint_sources([(_TPU003_PATH, typo), _SETTINGS_TWIN])
    assert rules_of(findings) == ["TPU003"]
    assert "ES_TPU_RAEL" in findings[0].message


# --------------------------------------------------------------------------
# TPU004 — dtype drift
# --------------------------------------------------------------------------

_TPU004_PATH = "elasticsearch_tpu/ops/scoring.py"


def test_tpu004_detects_literal_mixed_with_narrow_int():
    src = '''
def f(x):
    q = x.astype("int8")
    return q * 0.5
'''
    findings = lint_sources([(_TPU004_PATH, src)])
    assert rules_of(findings) == ["TPU004"]
    assert "`q`" in findings[0].message


def test_tpu004_division_of_narrow_array_flagged():
    src = '''
import jax.numpy as jnp

def f(x):
    h = jnp.zeros((4,), dtype=jnp.bfloat16)
    return h / 2
'''
    findings = lint_sources([(_TPU004_PATH, src)])
    assert rules_of(findings) == ["TPU004"]


def test_tpu004_clean_twin_passes():
    src = '''
import numpy as np

def f(x):
    q = x.astype("int8")
    wide = q.astype(np.float32)
    return wide * 0.5, q * 2
'''
    # explicit astype before float math; int * int literal is exact
    assert lint_sources([(_TPU004_PATH, src)]) == []


def test_tpu004_only_applies_to_kernel_files():
    src = 'def f(x):\n    q = x.astype("int8")\n    return q * 0.5\n'
    assert lint_sources([("elasticsearch_tpu/search/fixture.py", src)]) == []


def test_tpu004_suppression():
    src = '''
def f(x):
    q = x.astype("int8")
    return q * 0.5  # tpulint: disable=TPU004
'''
    assert lint_sources([(_TPU004_PATH, src)]) == []


# --------------------------------------------------------------------------
# TPU005 — counter hygiene
# --------------------------------------------------------------------------

_TPU005_PATH = "elasticsearch_tpu/common/fixture.py"

_TPU005_BAD = '''
class S:
    def __init__(self):
        self.hits = 0
        self.lost = 0

    def record(self):
        self.hits += 1
        self.lost += 1

    def stats(self):
        return {"hits": self.hits}
'''


def test_tpu005_detects_invisible_counter():
    findings = lint_sources([(_TPU005_PATH, _TPU005_BAD)])
    assert rules_of(findings) == ["TPU005"]
    assert "lost" in findings[0].message


def test_tpu005_clean_twin_passes():
    src = _TPU005_BAD.replace('return {"hits": self.hits}',
                              'return {"hits": self.hits, "lost": self.lost}')
    assert lint_sources([(_TPU005_PATH, src)]) == []


def test_tpu005_gauges_and_statless_classes_exempt():
    src = '''
class Gauge:
    def __init__(self):
        self.active = 0

    def enter(self):
        self.active += 1

    def leave(self):
        self.active -= 1

    def stats(self):
        return {}

class NoStats:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
'''
    assert lint_sources([(_TPU005_PATH, src)]) == []


def test_tpu005_suppression():
    src = _TPU005_BAD.replace("        self.lost += 1",
                              "        self.lost += 1  # tpulint: disable=TPU005")
    assert lint_sources([(_TPU005_PATH, src)]) == []


# TPU005's histogram-registry pass (PR 9): literal observe() sites must
# name a histogram declared in common/metrics.py, otherwise the metric
# never surfaces in `tpu_search_latency` and raises at runtime.

_METRICS_TWIN = (
    "elasticsearch_tpu/common/metrics.py",
    '''
def declare_histogram(name, kind, doc):
    pass

declare_histogram("device", "ms", "one device dispatch")
declare_histogram("queue_wait.search", "ms", "search pool wait")
''',
)


def test_tpu005_undeclared_observe_detected():
    bad = (_TPU005_PATH, '''
from elasticsearch_tpu.common import metrics

def record(ms):
    metrics.observe("devcie", ms)
''')
    findings = lint_sources([_METRICS_TWIN, bad], select={"TPU005"})
    assert rules_of(findings) == ["TPU005"]
    assert "devcie" in findings[0].message


def test_tpu005_declared_observe_clean():
    ok = (_TPU005_PATH, '''
from elasticsearch_tpu.common import metrics

def record(ms, pool):
    metrics.observe("device", ms)
    # dynamically composed names go through the lenient entry point,
    # which the rule deliberately ignores
    metrics.observe_if_declared(f"queue_wait.{pool}", ms)
''')
    assert lint_sources([_METRICS_TWIN, ok], select={"TPU005"}) == []


def test_tpu005_observe_pass_needs_registry_in_scope():
    """Without metrics.py in the lint scope there is no declaration set, so
    the rule must stay silent (fixture snippets would otherwise light up)."""
    orphan = (_TPU005_PATH, '''
from elasticsearch_tpu.common import metrics

def record(ms):
    metrics.observe("anything_at_all", ms)
''')
    assert lint_sources([orphan], select={"TPU005"}) == []


# TPU005's gauge-surface pass (PR 12): a file that declares a gauge must
# also surface it — the dotted tail has to appear as a key in some *stats()
# function in the same file, otherwise the gauge scrapes over /_tpu/metrics
# but is invisible in its owning `_nodes/stats` section.

_TPU005_GAUGE_BAD = '''
from elasticsearch_tpu.common import metrics

metrics.declare_gauge("tpu_widget.occupancy_bytes", "bytes resident")

def widget_stats():
    return {"evictions": 0}
'''


def test_tpu005_unsurfaced_gauge_detected():
    findings = lint_sources([(_TPU005_PATH, _TPU005_GAUGE_BAD)],
                            select={"TPU005"})
    assert rules_of(findings) == ["TPU005"]
    assert "tpu_widget.occupancy_bytes" in findings[0].message


def test_tpu005_surfaced_gauge_clean():
    ok = _TPU005_GAUGE_BAD.replace(
        'return {"evictions": 0}',
        'return {"evictions": 0, "occupancy_bytes": 0}')
    assert lint_sources([(_TPU005_PATH, ok)], select={"TPU005"}) == []


def test_tpu005_gauge_pass_exempts_metrics_registry():
    """common/metrics.py holds the central cross-subsystem declarations
    (e.g. scheduler gauges) whose stats() surfaces live elsewhere."""
    registry = ("elasticsearch_tpu/common/metrics.py", _TPU005_GAUGE_BAD)
    assert lint_sources([registry], select={"TPU005"}) == []


# --------------------------------------------------------------------------
# Baseline machinery
# --------------------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    p = tmp_path / "baseline.txt"
    p.write_text("# comment\n\na/b.py:10: TPU001 legacy tier\n")
    entries = load_baseline(str(p))
    assert entries == {("a/b.py", 10, "TPU001"): "legacy tier"}
    f_known = Finding("TPU001", "a/b.py", 10, 0, "m")
    f_new = Finding("TPU002", "a/b.py", 11, 0, "m")
    fresh, stale = apply_baseline([f_known, f_new], entries)
    assert fresh == [f_new] and stale == []
    fresh, stale = apply_baseline([f_new], entries)
    assert fresh == [f_new] and stale == [("a/b.py", 10, "TPU001")]


def test_baseline_rejects_reasonless_and_garbage(tmp_path):
    p = tmp_path / "baseline.txt"
    p.write_text("a/b.py:10: TPU001\n")
    with pytest.raises(ValueError):
        load_baseline(str(p))
    p.write_text("not a baseline line\n")
    with pytest.raises(ValueError):
        load_baseline(str(p))


# --------------------------------------------------------------------------
# The package-wide gate + baseline meta-tests
# --------------------------------------------------------------------------


def test_package_lints_clean_against_baseline():
    findings = lint_paths(["elasticsearch_tpu"], root=str(ROOT))
    fresh, stale = apply_baseline(findings, load_baseline(str(BASELINE)))
    assert not fresh, "non-baselined findings:\n" + "\n".join(
        f.render() for f in fresh)
    assert not stale, "stale baseline entries (code moved — re-justify " \
        "or drop):\n" + "\n".join(f"{p}:{ln}: {r}" for p, ln, r in stale)


def test_baseline_references_live_lines():
    for (path, line, rule), reason in load_baseline(str(BASELINE)).items():
        src = ROOT / path
        assert src.exists(), f"baseline references missing file {path}"
        n_lines = len(src.read_text().splitlines())
        assert 1 <= line <= n_lines, \
            f"baseline {path}:{line} ({rule}) is past EOF ({n_lines} lines)"
        assert reason.strip(), f"baseline {path}:{line} has no reason"


def test_cli_exits_clean(capsys, monkeypatch):
    from tools.tpulint.__main__ import main

    monkeypatch.chdir(ROOT)
    assert main(["elasticsearch_tpu"]) == 0
    out = capsys.readouterr().out
    assert "OK" in out


# --------------------------------------------------------------------------
# Knob registry semantics (satellite 1)
# --------------------------------------------------------------------------


def test_knob_reads_env_per_call(monkeypatch):
    from elasticsearch_tpu.common.settings import knob

    monkeypatch.delenv("ES_TPU_HEALTH_TRIP_N", raising=False)
    assert knob("ES_TPU_HEALTH_TRIP_N") == 3
    monkeypatch.setenv("ES_TPU_HEALTH_TRIP_N", "5")
    assert knob("ES_TPU_HEALTH_TRIP_N") == 5
    monkeypatch.setenv("ES_TPU_HEALTH_TRIP_N", "junk")
    assert knob("ES_TPU_HEALTH_TRIP_N") == 3      # lenient fallback


def test_knob_flag_semantics(monkeypatch):
    from elasticsearch_tpu.common.settings import knob

    monkeypatch.setenv("ES_TPU_FORCE_TURBO", "1")
    assert knob("ES_TPU_FORCE_TURBO") is True
    monkeypatch.setenv("ES_TPU_FORCE_TURBO", "true")
    assert knob("ES_TPU_FORCE_TURBO") is False    # exactly "1" means on


def test_knob_undeclared_raises():
    from elasticsearch_tpu.common.settings import UndeclaredKnobError, knob

    with pytest.raises(UndeclaredKnobError):
        knob("ES_TPU_NO_SUCH_KNOB")


def test_effective_knobs_reports_source(monkeypatch):
    from elasticsearch_tpu.common.settings import effective_knobs

    monkeypatch.setenv("ES_TPU_FAULTS_SEED", "7")
    monkeypatch.delenv("ES_TPU_HEALTH_TRIP_N", raising=False)
    eff = effective_knobs()
    assert eff["ES_TPU_FAULTS_SEED"]["value"] == 7
    assert eff["ES_TPU_FAULTS_SEED"]["source"] == "env"
    assert eff["ES_TPU_HEALTH_TRIP_N"]["source"] == "default"
    assert eff["ES_TPU_HEALTH_TRIP_N"]["value"] == 3


def test_nodes_stats_exposes_tpu_settings():
    from elasticsearch_tpu.rest.handlers import _tpu_settings_stats

    eff = _tpu_settings_stats()
    assert "ES_TPU_FAULTS" in eff and "ES_TPU_TURBO_HBM" in eff
    for entry in eff.values():
        assert {"value", "default", "type", "source"} <= set(entry)

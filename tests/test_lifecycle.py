"""Index lifecycle admin: rollover, shrink/split/clone, open/close,
write-index aliases (VERDICT r4 item 7; ref:
action/admin/indices/{close,open,shrink,rollover},
cluster/metadata/MetadataRolloverService.java)."""

import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest import RestController, register_handlers


@pytest.fixture()
def api():
    node = Node()
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, params=None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body)
        return rc.dispatch(method, path, params or {}, body)

    yield call, node
    node.close()


def _seed(call, index, n, field="f"):
    for i in range(n):
        call("PUT", f"/{index}/_doc/{i}", {field: f"value {i}", "n": i})
    call("POST", f"/{index}/_refresh")


# ------------------------------------------------------------ open/close --


def test_close_blocks_data_ops_and_open_restores(api):
    call, _ = api
    call("PUT", "/c1", {})
    _seed(call, "c1", 3)
    r = call("POST", "/c1/_close")
    assert r.status == 200 and r.body["indices"]["c1"]["closed"]
    assert call("GET", "/c1/_search").status == 400
    assert call("PUT", "/c1/_doc/9", {"f": "x"}).status == 400
    assert "index_closed_exception" in str(
        call("GET", "/c1/_search").body)
    # metadata ops still answer
    assert call("GET", "/c1").status == 200
    r = call("POST", "/c1/_open")
    assert r.status == 200
    assert call("GET", "/c1/_search").status == 200
    assert call("GET", "/c1/_doc/0").status == 200


# -------------------------------------------------------------- rollover --


def test_rollover_no_conditions_always_rolls(api):
    call, node = api
    call("PUT", "/logs-000001", {"aliases": {"logs": {
        "is_write_index": True}}})
    _seed(call, "logs-000001", 2)
    r = call("POST", "/logs/_rollover")
    assert r.status == 200, r.body
    assert r.body["rolled_over"] is True
    assert r.body["old_index"] == "logs-000001"
    assert r.body["new_index"] == "logs-000002"
    # alias moved: new index is the write index, old keeps read alias
    meta_old = node.cluster_state.indices["logs-000001"]
    meta_new = node.cluster_state.indices["logs-000002"]
    assert meta_old.aliases["logs"]["is_write_index"] is False
    assert meta_new.aliases["logs"]["is_write_index"] is True


def test_rollover_conditions_and_dry_run(api):
    call, _ = api
    call("PUT", "/ro-000001", {"aliases": {"ro": {"is_write_index": True}}})
    _seed(call, "ro-000001", 5)
    r = call("POST", "/ro/_rollover", {"conditions": {"max_docs": 100}})
    assert r.body["rolled_over"] is False          # condition unmet
    r = call("POST", "/ro/_rollover", {"conditions": {"max_docs": 3},
                                       "dry_run": True})
    assert r.body["rolled_over"] is False and r.body["dry_run"] is True
    r = call("POST", "/ro/_rollover", {"conditions": {"max_docs": 3}})
    assert r.body["rolled_over"] is True
    assert r.body["new_index"] == "ro-000002"


def test_rollover_writes_follow_the_alias(api):
    call, _ = api
    call("PUT", "/w-000001", {"aliases": {"w": {"is_write_index": True}}})
    call("PUT", "/w/_doc/a", {"f": "first"})       # via alias
    call("POST", "/w/_rollover")
    call("PUT", "/w/_doc/b", {"f": "second"})      # lands in w-000002
    call("POST", "/w-000001/_refresh")
    call("POST", "/w-000002/_refresh")
    r1 = call("GET", "/w-000001/_search")
    r2 = call("GET", "/w-000002/_search")
    assert [h["_id"] for h in r1.body["hits"]["hits"]] == ["a"]
    assert [h["_id"] for h in r2.body["hits"]["hits"]] == ["b"]
    # searching the alias spans both
    ra = call("GET", "/w/_search")
    assert sorted(h["_id"] for h in ra.body["hits"]["hits"]) == ["a", "b"]


def test_bulk_writes_resolve_write_alias(api):
    call, _ = api
    call("PUT", "/bw-000001", {"aliases": {"bw": {"is_write_index": True}}})
    nd = '{"index":{"_index":"bw","_id":"1"}}\n{"f":"x"}\n'
    r = call("POST", "/_bulk", nd)
    assert r.status == 200 and not r.body["errors"]
    call("POST", "/bw-000001/_refresh")
    r = call("GET", "/bw-000001/_search")
    assert [h["_id"] for h in r.body["hits"]["hits"]] == ["1"]


def test_rollover_ambiguous_alias_rejected(api):
    call, _ = api
    call("PUT", "/amb-1", {"aliases": {"amb": {}}})
    call("PUT", "/amb-2", {"aliases": {"amb": {}}})
    r = call("POST", "/amb/_rollover")
    assert r.status == 400


# ---------------------------------------------------------------- resize --


def test_shrink_reduces_shards_and_keeps_docs(api):
    call, _ = api
    call("PUT", "/big", {"settings": {"number_of_shards": 4}})
    _seed(call, "big", 20)
    r = call("PUT", "/big/_shrink/small",
             {"settings": {"index.number_of_shards": 2}})
    assert r.status == 200, r.body
    r = call("GET", "/small/_count")
    assert r.body["count"] == 20
    meta = call("GET", "/small").body["small"]
    assert meta["settings"]["index"]["number_of_shards"] == "2"
    # every doc retrievable (routing re-partitioned correctly)
    for i in range(20):
        assert call("GET", f"/small/_doc/{i}").status == 200


def test_split_multiplies_shards(api):
    call, _ = api
    call("PUT", "/narrow", {"settings": {"number_of_shards": 2}})
    _seed(call, "narrow", 12)
    r = call("PUT", "/narrow/_split/wide",
             {"settings": {"index.number_of_shards": 4}})
    assert r.status == 200, r.body
    assert call("GET", "/wide/_count").body["count"] == 12


def test_clone_keeps_shard_count(api):
    call, _ = api
    call("PUT", "/orig", {"settings": {"number_of_shards": 2},
                          "mappings": {"properties": {
                              "f": {"type": "text"}}}})
    _seed(call, "orig", 6)
    call("DELETE", "/orig/_doc/0")
    call("POST", "/orig/_refresh")
    r = call("PUT", "/orig/_clone/copy")
    assert r.status == 200, r.body
    assert call("GET", "/copy/_count").body["count"] == 5   # delete honored
    # searches behave identically
    q = {"query": {"match": {"f": "value"}}}
    a = call("POST", "/orig/_search", q).body["hits"]["total"]
    b = call("POST", "/copy/_search", q).body["hits"]["total"]
    assert a == b


def test_shrink_factor_validation(api):
    call, _ = api
    call("PUT", "/odd", {"settings": {"number_of_shards": 3}})
    r = call("PUT", "/odd/_shrink/bad",
             {"settings": {"index.number_of_shards": 2}})
    assert r.status == 400


def test_resize_target_exists_rejected(api):
    call, _ = api
    call("PUT", "/r1", {})
    call("PUT", "/r2", {})
    assert call("PUT", "/r1/_clone/r2").status == 400

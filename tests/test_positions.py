"""Vectorized positional phrase kernel vs a brute-force per-doc reference.

The columnar searchsorted kernel (index/positions.py) must return the same
(doc, phrase_freq) pairs as a direct per-doc position-list walk — Lucene
ExactPhraseMatcher / sloppy window semantics — over randomized corpora, and
the columnar bulk builder must record the SAME positions CSR as the per-doc
SegmentBuilder.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.positions import _offset_tuples, phrase_freqs
from elasticsearch_tpu.index.segment import SegmentBuilder, build_field_postings
from elasticsearch_tpu.mapper.mapper_service import LuceneDoc


def make_fp(rng, n_docs=300, vocab=12, min_len=3, max_len=30):
    """Small dense-vocab corpus (phrases actually match) via the bulk builder."""
    lens = rng.integers(min_len, max_len, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum())).astype(np.int64)
    tok_docs = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
    tok_pos = np.concatenate([np.arange(n, dtype=np.int64) for n in lens])
    names = [f"t{i:02d}" for i in range(vocab)]
    fp = build_field_postings("body", lens, tok_docs, tokens, names,
                              token_pos=tok_pos)
    # doc -> token list for the reference matcher
    doc_tokens = np.split(tokens, np.cumsum(lens)[:-1])
    return fp, doc_tokens, names


def ref_phrase_freq(doc_tokens, term_ords, slop):
    """Per-doc reference: the executor's original per-candidate loop."""
    positions = [np.nonzero(doc_tokens == t)[0] for t in term_ords]
    if any(len(p) == 0 for p in positions):
        return 0.0
    pos_sets = [set(p.tolist()) for p in positions]
    count = 0
    for p0 in positions[0]:
        for offs in _offset_tuples(len(positions), slop):
            if all((p0 + i + offs[i]) in pos_sets[i]
                   for i in range(1, len(positions))):
                count += 1
                break
    return float(count)


@pytest.mark.parametrize("slop", [0, 1, 2])
@pytest.mark.parametrize("n_terms", [2, 3, 4])
def test_phrase_freqs_matches_brute_force(slop, n_terms):
    rng = np.random.default_rng(100 * slop + n_terms)
    fp, doc_tokens, names = make_fp(rng)
    for trial in range(20):
        term_ords = rng.choice(len(names), size=n_terms, replace=True)
        terms = [names[t] for t in term_ords]
        docs, freqs = phrase_freqs(fp, terms, slop=slop)
        got = dict(zip(docs.tolist(), freqs.tolist()))
        want = {}
        for d, toks in enumerate(doc_tokens):
            f = ref_phrase_freq(toks, term_ords, slop)
            if f > 0:
                want[d] = f
        assert got == want, f"slop={slop} terms={terms}"


def test_phrase_freqs_single_term_is_tf():
    rng = np.random.default_rng(7)
    fp, doc_tokens, names = make_fp(rng)
    docs, freqs = phrase_freqs(fp, [names[3]], slop=0)
    for d, f in zip(docs, freqs):
        assert f == float(np.count_nonzero(doc_tokens[d] == 3))


def test_phrase_freqs_missing_term():
    rng = np.random.default_rng(8)
    fp, _, names = make_fp(rng)
    docs, freqs = phrase_freqs(fp, [names[0], "zzz-absent"], slop=0)
    assert len(docs) == 0 and len(freqs) == 0


def test_phrase_freqs_rejects_positionless_build():
    """Segments bulk-built WITHOUT token_pos must raise on phrase, not
    silently match nothing (VERDICT r2 weak #5)."""
    rng = np.random.default_rng(9)
    lens = rng.integers(3, 10, size=50).astype(np.int64)
    tokens = rng.choice(5, size=int(lens.sum())).astype(np.int64)
    names = [f"t{i}" for i in range(5)]
    fp = build_field_postings(
        "body", lens, np.repeat(np.arange(50, dtype=np.int64), lens),
        tokens, names)
    with pytest.raises(ValueError, match="without positions"):
        phrase_freqs(fp, [names[0], names[1]], slop=0)


def test_bulk_builder_positions_match_slow_builder():
    """token_pos -> identical positions CSR as the per-doc SegmentBuilder."""
    rng = np.random.default_rng(5)
    n_docs, vocab = 120, 15
    lens = rng.integers(1, 25, size=n_docs).astype(np.int64)
    tokens = rng.choice(vocab, size=int(lens.sum())).astype(np.int64)
    names = [f"t{i:02d}" for i in range(vocab)]
    tok_pos = np.concatenate([np.arange(n, dtype=np.int64) for n in lens])

    fast = build_field_postings(
        "body", lens, np.repeat(np.arange(n_docs, dtype=np.int64), lens),
        tokens, names, token_pos=tok_pos)

    builder = SegmentBuilder()
    off = 0
    for i in range(n_docs):
        n = int(lens[i])
        doc_toks = tokens[off:off + n]
        off += n
        doc = LuceneDoc(doc_id=str(i), source={})
        by_term = {}
        for p, t in enumerate(doc_toks):
            by_term.setdefault(int(t), []).append(p)
        doc.inverted["body"] = [(names[t], ps) for t, ps in sorted(by_term.items())]
        doc.field_lengths["body"] = n
        builder.add(doc, seq_no=i)
    slow = builder.build().postings["body"]

    for t in slow.terms:
        o_f = fast.term_to_ord[t]
        lo_f, hi_f = int(fast.post_start[o_f]), int(fast.post_start[o_f + 1])
        o_s = slow.term_to_ord[t]
        lo_s, hi_s = int(slow.post_start[o_s]), int(slow.post_start[o_s + 1])
        np.testing.assert_array_equal(fast.post_doc[lo_f:hi_f],
                                      slow.post_doc[lo_s:hi_s])
        for j in range(hi_f - lo_f):
            pf, ps = lo_f + j, lo_s + j
            np.testing.assert_array_equal(
                fast.pos_data[int(fast.pos_start[pf]):int(fast.pos_start[pf + 1])],
                slow.pos_data[int(slow.pos_start[ps]):int(slow.pos_start[ps + 1])],
                err_msg=f"term {t} posting {j}")


def test_blockmax_search_phrase_matches_executor_semantics():
    """search_phrase over stacked shards == per-doc reference scoring."""
    from elasticsearch_tpu.parallel import build_stacked_bm25, make_mesh
    from elasticsearch_tpu.parallel.blockmax import BlockMaxBM25
    from elasticsearch_tpu.ops import bm25_idf

    rng = np.random.default_rng(21)
    n_shards = 2
    fps, all_doc_tokens = [], []
    segs = []
    for s in range(n_shards):
        fp, doc_tokens, names = make_fp(rng, n_docs=200, vocab=10)

        class _Seg:
            pass

        seg = _Seg()
        seg.n_docs = len(doc_tokens)
        seg.postings = {"body": fp}
        segs.append(seg)
        fps.append(fp)
        all_doc_tokens.append(doc_tokens)

    mesh = make_mesh(1, dp=1)
    stacked = build_stacked_bm25(segs, "body", mesh=mesh)
    serving = BlockMaxBM25(stacked, mesh)

    phrase = [names[2], names[5]]
    s_arr, sh_arr, o_arr = serving.search_phrase([phrase], k=10, slop=0)

    # reference: brute-force phrase freq + BM25 with global stats
    K1, B_ = 1.2, 0.75
    idf_sum = sum(
        bm25_idf(stacked.total_docs,
                 sum(int(fp.doc_freq[fp.term_to_ord[t]]) for fp in fps
                     if t in fp.term_to_ord))
        for t in phrase)
    expect = []
    for s in range(n_shards):
        for d, toks in enumerate(all_doc_tokens[s]):
            pf = ref_phrase_freq(toks, [2, 5], 0)
            if pf > 0:
                dl = len(toks)
                sc = idf_sum * pf * (K1 + 1) / (
                    pf + K1 * (1 - B_ + B_ * dl / stacked.avgdl))
                expect.append((sc, s, d))
    expect.sort(key=lambda x: (-x[0], x[1], x[2]))
    top = expect[:10]
    assert len(top) > 0, "test corpus produced no phrase matches"
    got = [(float(s_arr[0][i]), int(sh_arr[0][i]), int(o_arr[0][i]))
           for i in range(len(top))]
    for (es, esh, eo), (gs, gsh, go) in zip(top, got):
        assert abs(es - gs) < 1e-4
        assert (esh, eo) == (gsh, go)

"""Columnar segment merge (VERDICT r2 weak #9): merge_segments must agree
with rebuilding every live doc through the mapper, across every column
family, with deletes."""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService

MAPPINGS = {"properties": {
    "body": {"type": "text"},
    "tag": {"type": "keyword"},
    "n": {"type": "integer"},
    "loc": {"type": "geo_point"},
    "emb": {"type": "dense_vector", "dims": 4},
    "comments": {"type": "nested", "properties": {
        "who": {"type": "keyword"}, "text": {"type": "text"}}},
}}

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon"]


def make_service(seed=7, n_docs=240, refresh_every=60):
    meta = IndexMetadata(index="m", uuid="u", settings=Settings({}),
                         mappings=MAPPINGS)
    svc = IndexService(meta)
    rng = np.random.default_rng(seed)
    for i in range(n_docs):
        doc = {
            "body": " ".join(rng.choice(WORDS,
                                        size=int(rng.integers(2, 9)))),
            "tag": [f"g{rng.integers(0, 6)}"
                    for _ in range(int(rng.integers(1, 3)))],
            "n": [int(rng.integers(0, 50))
                  for _ in range(int(rng.integers(1, 3)))],
        }
        if i % 3 == 0:
            doc["loc"] = {"lat": float(rng.uniform(-80, 80)),
                          "lon": float(rng.uniform(-170, 170))}
        if i % 4 == 0:
            doc["emb"] = [float(x) for x in rng.standard_normal(4)]
        if i % 5 == 0:
            doc["comments"] = [
                {"who": f"u{rng.integers(0, 4)}",
                 "text": " ".join(rng.choice(WORDS, size=3))}
                for _ in range(int(rng.integers(1, 3)))]
        svc.index_doc(str(i), doc)
        if i % refresh_every == refresh_every - 1:
            svc.refresh()
    for i in range(0, n_docs, 7):
        svc.delete_doc(str(i))
    svc.refresh()
    return svc


QUERIES = [
    {"query": {"match": {"body": "alpha beta"}}, "size": 30,
     "track_total_hits": True},
    {"query": {"bool": {"must": [{"term": {"body": "gamma"}}],
                        "filter": [{"term": {"tag": "g2"}}]}}, "size": 30},
    {"query": {"range": {"n": {"gte": 20, "lte": 40}}}, "size": 30,
     "sort": [{"n": "asc"}], "track_total_hits": True},
    {"query": {"match_phrase": {"body": "alpha beta"}}, "size": 30},
    {"query": {"geo_distance": {"distance": "3000km",
                                "loc": {"lat": 10, "lon": 10}}}, "size": 30},
    {"query": {"nested": {"path": "comments",
                          "query": {"match": {"comments.text": "alpha"}}}},
     "size": 30},
    {"query": {"fuzzy": {"body": "alpa"}}, "size": 30},
    {"size": 0, "aggs": {"tags": {"terms": {"field": "tag", "size": 10}},
                         "s": {"sum": {"field": "n"}}},
     "track_total_hits": True},
    {"knn": {"field": "emb", "query_vector": [0.5, -0.2, 0.1, 0.9], "k": 5},
     "size": 5},
]


def results(svc, body):
    r = svc._search_dense(dict(body))
    hits = [(h["_id"], None if h.get("_score") is None
             else round(h["_score"], 5)) for h in r["hits"]["hits"]]
    return hits, r["hits"].get("total"), r.get("aggregations")


def test_columnar_merge_preserves_all_results():
    """After merging, results must equal a clean single-segment index of
    the LIVE docs (merges expunge deletes, so stats legitimately shift vs
    the pre-merge multi-segment view — Lucene semantics)."""
    svc = make_service()
    assert svc.shards[0].segment_count() > 1
    # reference: reindex the live docs in merged order, one refresh
    engine = svc.shards[0]
    meta = IndexMetadata(index="m", uuid="u2", settings=Settings({}),
                         mappings=MAPPINGS)
    ref = IndexService(meta)
    for seg, keep in zip(engine._segments, engine._live):
        for ord_ in range(seg.n_docs):
            if keep[ord_]:
                ref.index_doc(seg.doc_ids[ord_], seg.sources[ord_])
    ref.refresh()

    svc.force_merge(1)
    assert svc.shards[0].segment_count() == 1
    for q in QUERIES:
        a = results(svc, q)
        b = results(ref, q)
        assert a[0] == b[0] and a[2] == b[2], f"merge changed results for {q}"
        assert a[1] == b[1]
    ref.close()
    # writes continue after merge: update + delete against merged entries
    svc.index_doc("5", {"body": "alpha fresh", "tag": "g0", "n": 1})
    svc.delete_doc("8")
    svc.refresh()
    r = svc.search({"query": {"match": {"body": "fresh"}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["5"]
    assert svc.get_doc("8") is None
    svc.close()


def test_merge_matches_reparse_builder_exactly():
    """The columnar merge must produce the SAME postings as re-parsing all
    live docs through the mapper (the previous merge implementation)."""
    from elasticsearch_tpu.index.segment import SegmentBuilder, merge_segments

    svc = make_service(seed=11, n_docs=120, refresh_every=40)
    engine = svc.shards[0]
    segments, live = engine._segments, engine._live
    merged = merge_segments(segments, live, seg_id=99)

    builder = SegmentBuilder(seg_id=99)
    for seg, keep in zip(segments, live):
        for ord_ in range(seg.n_docs):
            if keep[ord_]:
                doc = svc.mapper.parse(seg.doc_ids[ord_], seg.sources[ord_])
                builder.add(doc, seq_no=int(seg.seq_nos[ord_]),
                            version=int(seg.versions[ord_]))
    ref = builder.build()

    assert merged.doc_ids == ref.doc_ids
    np.testing.assert_array_equal(merged.seq_nos, ref.seq_nos)
    for field in ref.postings:
        mf, rf = merged.postings[field], ref.postings[field]
        live_terms = [t for t in rf.terms if rf.doc_freq[rf.term_to_ord[t]] > 0]
        merged_live = [t for t in mf.terms if mf.doc_freq[mf.term_to_ord[t]] > 0]
        assert merged_live == live_terms, field
        np.testing.assert_array_equal(mf.doc_len, rf.doc_len)
        for t in live_terms:
            om, orf = mf.term_to_ord[t], rf.term_to_ord[t]
            assert mf.doc_freq[om] == rf.doc_freq[orf], (field, t)
            assert mf.total_term_freq[om] == rf.total_term_freq[orf]
            lo_m, hi_m = int(mf.post_start[om]), int(mf.post_start[om + 1])
            lo_r, hi_r = int(rf.post_start[orf]), int(rf.post_start[orf + 1])
            np.testing.assert_array_equal(mf.post_doc[lo_m:hi_m],
                                          rf.post_doc[lo_r:hi_r])
            for j in range(hi_m - lo_m):
                np.testing.assert_array_equal(
                    mf.pos_data[int(mf.pos_start[lo_m + j]):
                                int(mf.pos_start[lo_m + j + 1])],
                    rf.pos_data[int(rf.pos_start[lo_r + j]):
                                int(rf.pos_start[lo_r + j + 1])],
                    err_msg=f"{field}/{t} posting {j}")
    for field in ref.numeric:
        mn, rn = merged.numeric[field], ref.numeric[field]
        np.testing.assert_array_equal(mn.values, rn.values)
        np.testing.assert_array_equal(mn.exists, rn.exists)
        np.testing.assert_array_equal(mn.all_values, rn.all_values)
        np.testing.assert_array_equal(mn.value_start, rn.value_start)
    for field in ref.keyword:
        mk, rk = merged.keyword[field], ref.keyword[field]
        # compare per-doc TERM LISTS (dictionary ord layouts may differ)
        for d in range(merged.n_docs):
            assert mk.doc_terms(d) == rk.doc_terms(d), (field, d)
    for field in ref.geo:
        mg, rg = merged.geo[field], ref.geo[field]
        np.testing.assert_array_equal(mg.lat, rg.lat)
        np.testing.assert_array_equal(mg.lon, rg.lon)
        np.testing.assert_array_equal(mg.value_start, rg.value_start)
    for field in ref.vectors:
        np.testing.assert_array_equal(merged.vectors[field].vectors,
                                      ref.vectors[field].vectors)
    for field in ref.nested:
        mt, rt = merged.nested[field], ref.nested[field]
        np.testing.assert_array_equal(mt.parent_of, rt.parent_of)
        np.testing.assert_array_equal(mt.child_start, rt.child_start)
        assert mt.child.sources == rt.child.sources
    svc.close()


def test_merge_drops_dead_only_terms():
    """Review r3 finding: terms whose only postings were deleted must not
    survive merges (they would accumulate across merge generations)."""
    meta = IndexMetadata(index="dt", uuid="u", settings=Settings({}),
                         mappings={"properties": {
                             "body": {"type": "text"},
                             "tag": {"type": "keyword"}}})
    svc = IndexService(meta)
    svc.index_doc("1", {"body": "unique_zombie_term here", "tag": "onlyme"})
    svc.refresh()
    svc.index_doc("2", {"body": "normal words here", "tag": "keepme"})
    svc.refresh()
    svc.delete_doc("1")
    svc.refresh()
    assert svc.shards[0].segment_count() == 2
    svc.force_merge(1)
    seg = svc.shards[0].acquire_searcher().views[0].segment
    assert "unique_zombie_term" not in seg.postings["body"].term_to_ord
    assert "here" in seg.postings["body"].term_to_ord
    assert "onlyme" not in seg.keyword["tag"].term_to_ord
    assert "keepme" in seg.keyword["tag"].term_to_ord
    svc.close()

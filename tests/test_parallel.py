"""SPMD sharded search on a virtual 8-device CPU mesh: parity vs single-shard."""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import InternalEngine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.parallel import (
    build_stacked_bm25,
    build_stacked_knn,
    make_mesh,
    murmur3_hash,
    prepare_query_blocks,
    shard_for_id,
    sharded_bm25_topk,
    sharded_knn_topk,
)

MAPPING = {"properties": {"body": {"type": "text"}, "vec": {"type": "dense_vector", "dims": 16}}}

N_DOCS = 400
N_SHARDS = 4


def corpus(rng):
    vocab = [f"w{i}" for i in range(80)]
    probs = 1.0 / np.arange(1, len(vocab) + 1)
    probs /= probs.sum()
    docs = {}
    for i in range(N_DOCS):
        body = " ".join(rng.choice(vocab, size=int(rng.integers(4, 40)), p=probs))
        vec = rng.normal(size=16).astype(np.float32)
        docs[str(i)] = {"body": body, "vec": vec.tolist()}
    return docs


@pytest.fixture(scope="module")
def sharded():
    rng = np.random.default_rng(7)
    docs = corpus(rng)
    engines = [InternalEngine(MapperService(dict(MAPPING))) for _ in range(N_SHARDS)]
    single = InternalEngine(MapperService(dict(MAPPING)))
    for doc_id, src in docs.items():
        engines[shard_for_id(doc_id, N_SHARDS)].index(doc_id, src)
        single.index(doc_id, src)
    for e in engines:
        e.refresh()
    single.refresh()
    segments = [e.acquire_searcher().views[0].segment if e.acquire_searcher().views else None
                for e in engines]
    assert all(s is not None for s in segments)
    return docs, engines, segments, single


def test_murmur3_known_vectors():
    # public MurmurHash3 x86_32 reference vectors
    assert murmur3_hash("") == 0
    assert murmur3_hash("hello") == 0x248BFA47
    assert murmur3_hash("The quick brown fox jumps over the lazy dog") == 0x2E4FF723


def test_routing_distribution():
    counts = np.zeros(N_SHARDS)
    for i in range(2000):
        counts[shard_for_id(str(i), N_SHARDS)] += 1
    assert counts.min() > 2000 / N_SHARDS * 0.7


def test_sharded_bm25_matches_single_shard(sharded):
    docs, engines, segments, single = sharded
    mesh = make_mesh(4, dp=1)
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    queries = [["w0", "w3"], ["w1"], ["w5", "w9", "w21"], ["w2", "w40"]]
    qb, qi = prepare_query_blocks(stacked, queries)
    top_s, shard_of, ord_of = sharded_bm25_topk(mesh, stacked, qb, qi, k=10)

    # reference: single-shard engine search (same global stats by construction)
    from elasticsearch_tpu.search import execute_search

    for qn, terms in enumerate(queries):
        req = {"query": {"match": {"body": " ".join(terms)}}, "size": 10}
        ref = execute_search(single.acquire_searcher(), single.mapper, req, "t")
        ref_ids = [h["_id"] for h in ref["hits"]["hits"]]
        ref_scores = [h["_score"] for h in ref["hits"]["hits"]]
        got_ids = []
        got_scores = []
        for s, sh, o in zip(top_s[qn], shard_of[qn], ord_of[qn]):
            if not np.isfinite(s):
                break
            got_ids.append(segments[sh].doc_ids[o])
            got_scores.append(float(s))
        np.testing.assert_allclose(got_scores, ref_scores[: len(got_scores)], rtol=1e-4)
        # identical hit sets modulo equal-score tie order
        assert set(got_ids) == set(ref_ids[: len(got_ids)]) or got_scores == pytest.approx(
            ref_scores[: len(got_scores)], rel=1e-4)


def test_sharded_bm25_with_dp_axis(sharded):
    docs, engines, segments, single = sharded
    mesh = make_mesh(8, dp=2)
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    queries = [["w0"], ["w1"], ["w2"], ["w3"]]  # Q=4 divides dp=2
    qb, qi = prepare_query_blocks(stacked, queries)
    top_s, shard_of, ord_of = sharded_bm25_topk(mesh, stacked, qb, qi, k=5)
    assert top_s.shape == (4, 5)
    # every query's best hit must actually contain the term
    for qn, terms in enumerate(queries):
        best = segments[shard_of[qn, 0]]
        src_body = best.sources[ord_of[qn, 0]]["body"]
        assert terms[0] in src_body.split()


def test_sharded_knn_matches_bruteforce(sharded):
    docs, engines, segments, single = sharded
    mesh = make_mesh(4, dp=1)
    stacked = build_stacked_knn(segments, "vec", mesh=mesh)
    rng = np.random.default_rng(3)
    queries = rng.normal(size=(3, 16)).astype(np.float32)
    top_s, shard_of, ord_of = sharded_knn_topk(mesh, stacked, queries, k=5)

    all_ids = sorted(docs)
    mat = np.stack([np.asarray(docs[d]["vec"], np.float32) for d in all_ids])
    for qn in range(3):
        cos = mat @ queries[qn] / (np.linalg.norm(mat, axis=1) * np.linalg.norm(queries[qn]))
        want = [all_ids[i] for i in np.argsort(-cos)[:5]]
        got = [segments[sh].doc_ids[o] for sh, o in zip(shard_of[qn], ord_of[qn])]
        assert got == want


def test_live_mask_excludes_deleted(sharded):
    docs, engines, segments, single = sharded
    mesh = make_mesh(4, dp=1)
    # kill the globally best doc for "w0" and verify it vanishes
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    qb, qi = prepare_query_blocks(stacked, [["w0"]])
    top_s, shard_of, ord_of = sharded_bm25_topk(mesh, stacked, qb, qi, k=3)
    best_shard, best_ord = int(shard_of[0, 0]), int(ord_of[0, 0])
    best_id = segments[best_shard].doc_ids[best_ord]
    live = [np.ones(seg.n_docs, bool) for seg in segments]
    live[best_shard][best_ord] = False
    stacked2 = build_stacked_bm25(segments, "body", live_masks=live, mesh=mesh)
    top_s2, shard_of2, ord_of2 = sharded_bm25_topk(mesh, stacked2, qb, qi, k=3)
    ids2 = [segments[sh].doc_ids[o] for sh, o in zip(shard_of2[0], ord_of2[0])]
    assert best_id not in ids2


def test_column_cache_matches_block_path(sharded):
    from elasticsearch_tpu.parallel.spmd import Bm25ColumnCache

    docs, engines, segments, single = sharded
    mesh = make_mesh(4, dp=1)
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    cache = Bm25ColumnCache(stacked, mesh, capacity=64)
    queries = [["w0", "w3"], ["w1"], ["w5", "w9", "w21"], ["w2", "w40"]]

    qb, qi = prepare_query_blocks(stacked, queries)
    ref_s, ref_sh, ref_o = sharded_bm25_topk(mesh, stacked, qb, qi, k=10)
    got_s, got_sh, got_o = cache.search(queries, k=10)
    np.testing.assert_allclose(got_s, ref_s, rtol=1e-4)
    finite = np.isfinite(ref_s)
    assert (got_sh[finite] == ref_sh[finite]).mean() > 0.95

    # second batch reuses cached columns (w0/w1 hot) + adds a cold term
    queries2 = [["w0"], ["w1", "w60"]]
    got2_s, got2_sh, got2_o = cache.search(queries2, k=5)
    qb2, qi2 = prepare_query_blocks(stacked, queries2)
    ref2_s, _, _ = sharded_bm25_topk(mesh, stacked, qb2, qi2, k=5)
    np.testing.assert_allclose(got2_s, ref2_s, rtol=1e-4)


def test_column_cache_eviction():
    rng = np.random.default_rng(11)
    docs = corpus(rng)
    from elasticsearch_tpu.parallel.spmd import Bm25ColumnCache

    engines = [InternalEngine(MapperService(dict(MAPPING))) for _ in range(2)]
    for doc_id, src in docs.items():
        engines[shard_for_id(doc_id, 2)].index(doc_id, src)
    for e in engines:
        e.refresh()
    segments = [e.acquire_searcher().views[0].segment for e in engines]
    mesh = make_mesh(2, dp=1)
    stacked = build_stacked_bm25(segments, "body", mesh=mesh)
    cache = Bm25ColumnCache(stacked, mesh, capacity=4)
    cache.search([["w0", "w1"]], k=3)
    cache.search([["w2", "w3"]], k=3)
    s1, _, _ = cache.search([["w4", "w5"]], k=3)  # evicts w0/w1
    assert len(cache.term_slot) <= 4
    # re-query evicted terms: rebuilt correctly
    qb, qi = prepare_query_blocks(stacked, [["w0", "w1"]])
    ref_s, _, _ = sharded_bm25_topk(mesh, stacked, qb, qi, k=3)
    got_s, _, _ = cache.search([["w0", "w1"]], k=3)
    np.testing.assert_allclose(got_s, ref_s, rtol=1e-4)


def test_column_cache_never_evicts_current_batch_terms():
    rng = np.random.default_rng(12)
    docs = corpus(rng)
    from elasticsearch_tpu.parallel.spmd import Bm25ColumnCache

    engine = InternalEngine(MapperService(dict(MAPPING)))
    for doc_id, src in docs.items():
        engine.index(doc_id, src)
    engine.refresh()
    seg = engine.acquire_searcher().views[0].segment
    mesh = make_mesh(1, dp=1)
    stacked = build_stacked_bm25([seg], "body", mesh=mesh)
    cache = Bm25ColumnCache(stacked, mesh, capacity=4)
    cache.search([["w0", "w1", "w2", "w3"]], k=3)
    # batch mixes 3 hot terms + 1 cold at full capacity: w3 (stale) must be
    # evicted, never the batch's own hot terms (regression: used to KeyError)
    s, sh, o = cache.search([["w0", "w1", "w2", "w4"]], k=3)
    assert set(cache.term_slot) == {"w0", "w1", "w2", "w4"}
    qb, qi = prepare_query_blocks(stacked, [["w0", "w1", "w2", "w4"]])
    ref_s, _, _ = sharded_bm25_topk(mesh, stacked, qb, qi, k=3)
    np.testing.assert_allclose(s, ref_s, rtol=1e-4)
    # a single batch needing more distinct terms than capacity cannot be
    # made resident at once: explicit error, not a corrupt cache
    import pytest as _pytest
    with _pytest.raises(ValueError):
        cache.search([["w0", "w1", "w2"], ["w4", "w5"]], k=3)


def test_packed_id_roundtrip_covers_subnormal_range():
    """r3 regression: ids < 2^23 bitcast to SUBNORMAL f32 patterns and the
    TPU flushed them to zero in flight (10M-doc corpus, ords silently became
    0). The biased packing must round-trip every id up to 2^24."""
    import jax.numpy as jnp
    import numpy as np

    from elasticsearch_tpu.parallel.spmd import (
        _pack_ids, pack_id_np, unpack_ids_np,
    )

    ids = np.asarray([0, 1, 127, 2**20, 2**23 - 1, 2**23, 2**24 - 1], np.int32)
    packed = np.asarray(_pack_ids(jnp.asarray(ids)))
    assert not np.any(np.abs(packed) < np.finfo(np.float32).tiny), \
        "packed patterns must be NORMAL floats (no subnormals to flush)"
    np.testing.assert_array_equal(unpack_ids_np(packed), ids)
    for i in ids:
        assert unpack_ids_np(np.asarray([pack_id_np(int(i))])).item() == i

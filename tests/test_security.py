"""Security v1: basic + API-key authn, role-based authz as a REST action
filter (VERDICT r4 item 9; ref: x-pack/.../authc/AuthenticationService.java:71,
authz/AuthorizationService.java:100)."""

import base64
import json

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest import RestController, register_handlers


def _basic(user, pw):
    return {"Authorization": "Basic " + base64.b64encode(
        f"{user}:{pw}".encode()).decode()}


@pytest.fixture()
def api():
    node = Node(settings=Settings({
        "xpack.security.enabled": "true",
        "bootstrap.password": "s3cret",
    }))
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, headers=None, params=None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body)
        return rc.dispatch(method, path, params or {}, body,
                           headers=headers)

    yield call, node
    node.close()


ELASTIC = _basic("elastic", "s3cret")


def test_anonymous_rejected_when_security_on(api):
    call, _ = api
    assert call("GET", "/").status == 401
    assert call("GET", "/x/_search").status == 401
    r = call("GET", "/", headers=ELASTIC)
    assert r.status == 200


def test_wrong_password_and_unknown_user_401(api):
    call, _ = api
    assert call("GET", "/", headers=_basic("elastic", "bad")).status == 401
    assert call("GET", "/", headers=_basic("nobody", "x")).status == 401


def test_authenticate_endpoint(api):
    call, _ = api
    r = call("GET", "/_security/_authenticate", headers=ELASTIC)
    assert r.status == 200
    assert r.body["username"] == "elastic"
    assert "superuser" in r.body["roles"]


def test_authz_matrix_reader_vs_writer(api):
    """The VERDICT's authz matrix: per-(role, action) allow/deny over
    index patterns."""
    call, _ = api
    # roles + users via the superuser
    assert call("PUT", "/_security/role/logs_reader", {
        "indices": [{"names": ["logs-*"], "privileges": ["read"]}]},
        headers=ELASTIC).status == 200
    assert call("PUT", "/_security/role/logs_writer", {
        "indices": [{"names": ["logs-*"],
                     "privileges": ["read", "write", "create_index"]}]},
        headers=ELASTIC).status == 200
    assert call("PUT", "/_security/user/bob", {
        "password": "bobpass", "roles": ["logs_reader"]},
        headers=ELASTIC).status == 200
    assert call("PUT", "/_security/user/amy", {
        "password": "amypass", "roles": ["logs_writer"]},
        headers=ELASTIC).status == 200
    call("PUT", "/logs-1", {}, headers=ELASTIC)
    call("PUT", "/secret-1", {}, headers=ELASTIC)
    call("PUT", "/logs-1/_doc/1", {"f": "v"}, headers=ELASTIC)
    call("POST", "/logs-1/_refresh", headers=ELASTIC)

    BOB = _basic("bob", "bobpass")
    AMY = _basic("amy", "amypass")
    matrix = [
        # (user, method, path, body, expected)
        (BOB, "GET", "/logs-1/_search", None, 200),
        (BOB, "GET", "/logs-1/_doc/1", None, 200),
        (BOB, "PUT", "/logs-1/_doc/2", {"f": "x"}, 403),
        (BOB, "GET", "/secret-1/_search", None, 403),
        (BOB, "PUT", "/logs-9", {}, 403),              # create_index
        (BOB, "DELETE", "/logs-1", None, 403),
        (BOB, "GET", "/_cluster/health", None, 403),   # cluster priv
        (AMY, "PUT", "/logs-1/_doc/2", {"f": "x"}, 201),
        (AMY, "PUT", "/logs-9", {}, 200),
        (AMY, "GET", "/logs-1/_search", None, 200),
        (AMY, "PUT", "/secret-1/_doc/1", {"f": "x"}, 403),
        (AMY, "DELETE", "/logs-1", None, 403),         # needs delete_index
        (AMY, "PUT", "/_security/user/eve",
         {"password": "p", "roles": []}, 403),         # manage_security
    ]
    for user, method, path, body, expect in matrix:
        r = call(method, path, body, headers=user)
        assert r.status == expect, (method, path, r.status, r.body)


def test_bulk_target_scoped_by_role(api):
    call, _ = api
    call("PUT", "/_security/role/lw", {
        "indices": [{"names": ["logs-*"], "privileges": ["write"]}]},
        headers=ELASTIC)
    call("PUT", "/_security/user/w1", {"password": "pw", "roles": ["lw"]},
         headers=ELASTIC)
    call("PUT", "/logs-a", {}, headers=ELASTIC)
    call("PUT", "/other", {}, headers=ELASTIC)
    W = _basic("w1", "pw")
    ok = '{"index":{"_index":"logs-a","_id":"1"}}\n{"f":"v"}\n'
    assert call("POST", "/_bulk", ok, headers=W).status == 200
    # a bulk smuggling a write to an out-of-scope index is rejected whole
    bad = ('{"index":{"_index":"logs-a","_id":"2"}}\n{"f":"v"}\n'
           '{"index":{"_index":"other","_id":"1"}}\n{"f":"v"}\n')
    assert call("POST", "/_bulk", bad, headers=W).status == 403


def test_api_key_roundtrip_and_invalidation(api):
    call, _ = api
    r = call("POST", "/_security/api_key", {"name": "ci"}, headers=ELASTIC)
    assert r.status == 200
    encoded = r.body["encoded"]
    key_hdr = {"Authorization": f"ApiKey {encoded}"}
    assert call("GET", "/_cluster/health", headers=key_hdr).status == 200
    auth = call("GET", "/_security/_authenticate", headers=key_hdr)
    assert auth.body["authentication_type"] == "api_key"
    call("DELETE", "/_security/api_key", {"id": r.body["id"]},
         headers=ELASTIC)
    assert call("GET", "/_cluster/health", headers=key_hdr).status == 401


def test_api_key_with_restricted_role_descriptors(api):
    call, _ = api
    call("PUT", "/logs-k", {}, headers=ELASTIC)
    r = call("POST", "/_security/api_key", {
        "name": "ro", "role_descriptors": {
            "ro": {"indices": [{"names": ["logs-*"],
                                "privileges": ["read"]}]}}},
        headers=ELASTIC)
    hdr = {"Authorization": f"ApiKey {r.body['encoded']}"}
    assert call("GET", "/logs-k/_search", headers=hdr).status == 200
    assert call("PUT", "/logs-k/_doc/1", {"f": "v"},
                headers=hdr).status == 403


def test_anonymous_roles_grant_configured_access():
    node = Node(settings=Settings({
        "xpack.security.enabled": "true",
        "xpack.security.authc.anonymous.roles": "monitoring_user",
    }))
    rc = RestController()
    register_handlers(node, rc)
    try:
        r = rc.dispatch("GET", "/_cluster/health", {}, None)
        assert r.status == 200                  # monitor granted anonymously
        r = rc.dispatch("PUT", "/idx", {}, "{}")
        assert r.status == 403                  # but nothing else
    finally:
        node.close()


def test_reindex_requires_source_read_and_dest_write(api):
    """_reindex is an INDEX action (read source + write dest), not a
    cluster action: cluster-manage alone must not copy data between
    indices the user cannot touch (ADVICE r5)."""
    call, _ = api
    call("PUT", "/_security/role/src_reader", {
        "indices": [{"names": ["src-*"], "privileges": ["read"]}]},
        headers=ELASTIC)
    call("PUT", "/_security/role/dst_writer", {
        "indices": [{"names": ["dst-*"], "privileges": ["write"]}]},
        headers=ELASTIC)
    call("PUT", "/_security/role/cluster_admin", {"cluster": ["manage"]},
         headers=ELASTIC)
    call("PUT", "/_security/user/mover", {
        "password": "mpass", "roles": ["src_reader", "dst_writer"]},
        headers=ELASTIC)
    call("PUT", "/_security/user/reader_only", {
        "password": "rpass", "roles": ["src_reader"]}, headers=ELASTIC)
    call("PUT", "/_security/user/ops", {
        "password": "opass", "roles": ["cluster_admin"]}, headers=ELASTIC)
    call("PUT", "/src-1", {}, headers=ELASTIC)
    call("PUT", "/dst-1", {}, headers=ELASTIC)
    call("PUT", "/secret-src", {}, headers=ELASTIC)
    call("PUT", "/src-1/_doc/1", {"f": "v"}, headers=ELASTIC)
    call("POST", "/src-1/_refresh", headers=ELASTIC)

    body = {"source": {"index": "src-1"}, "dest": {"index": "dst-1"}}
    # read(source) + write(dest) suffices — no cluster privilege needed
    r = call("POST", "/_reindex", body, headers=_basic("mover", "mpass"))
    assert r.status == 200, r.body
    # missing write on dest
    assert call("POST", "/_reindex", body,
                headers=_basic("reader_only", "rpass")).status == 403
    # cluster manage grants NO data access through reindex
    assert call("POST", "/_reindex", body,
                headers=_basic("ops", "opass")).status == 403
    # out-of-scope source: read privilege checked on the body's index
    assert call("POST", "/_reindex",
                {"source": {"index": "secret-src"},
                 "dest": {"index": "dst-1"}},
                headers=_basic("mover", "mpass")).status == 403
    # a body naming no indices demands the privileges on "*"
    assert call("POST", "/_reindex", {},
                headers=_basic("mover", "mpass")).status == 403
    assert call("POST", "/_reindex", body, headers=ELASTIC).status == 200


def test_aliases_actions_require_index_manage(api):
    """POST /_aliases names its target indices in the body: index
    `manage` on each, not a cluster privilege (same audit as _reindex)."""
    call, _ = api
    call("PUT", "/_security/role/logs_mgr", {
        "indices": [{"names": ["logs-*"], "privileges": ["manage"]}]},
        headers=ELASTIC)
    call("PUT", "/_security/role/cluster_admin2", {"cluster": ["manage"]},
         headers=ELASTIC)
    call("PUT", "/_security/user/mgr", {
        "password": "gpass", "roles": ["logs_mgr"]}, headers=ELASTIC)
    call("PUT", "/_security/user/ops2", {
        "password": "o2pass", "roles": ["cluster_admin2"]}, headers=ELASTIC)
    call("PUT", "/logs-al", {}, headers=ELASTIC)
    call("PUT", "/secret-al", {}, headers=ELASTIC)

    add_logs = {"actions": [{"add": {"index": "logs-al", "alias": "la"}}]}
    add_secret = {"actions": [{"add": {"index": "secret-al", "alias": "sa"}}]}
    assert call("POST", "/_aliases", add_logs,
                headers=_basic("mgr", "gpass")).status == 200
    # manage on logs-* does not reach secret-al
    assert call("POST", "/_aliases", add_secret,
                headers=_basic("mgr", "gpass")).status == 403
    # cluster manage alone cannot repoint aliases over data indices
    assert call("POST", "/_aliases", add_logs,
                headers=_basic("ops2", "o2pass")).status == 403
    assert call("POST", "/_aliases", add_secret, headers=ELASTIC).status == 200


def test_scripts_stay_cluster_scoped():
    """Stored scripts are cluster metadata (ref: cluster:admin/script/put):
    _scripts classifies as a CLUSTER action, unlike _reindex/_aliases
    which name data indices in their bodies."""
    from elasticsearch_tpu.rest.controller import RestRequest
    from elasticsearch_tpu.security.service import _classify

    req = RestRequest(method="PUT", path="/_scripts/s1", params={},
                      body={"script": {"lang": "painless", "source": "1"}},
                      raw_body=b"", headers={})
    kind, priv, indices = _classify(req, ["_scripts", "s1"])
    assert kind == "cluster" and indices is None

    # ...while _reindex demands read(source) + write(dest) on the body's
    # indices, and _aliases demands manage on each named index
    req = RestRequest(method="POST", path="/_reindex", params={},
                      body={"source": {"index": ["a", "b"]},
                            "dest": {"index": "c"}},
                      raw_body=b"", headers={})
    kind, priv, _ = _classify(req, ["_reindex"])
    assert kind == "multi"
    assert ("read", ["a", "b"]) in priv and ("write", ["c"]) in priv

    req = RestRequest(method="POST", path="/_aliases", params={},
                      body={"actions": [
                          {"add": {"index": "x", "alias": "al"}},
                          {"remove": {"indices": ["y", "z"], "alias": "al"}},
                      ]}, raw_body=b"", headers={})
    kind, priv, indices = _classify(req, ["_aliases"])
    assert (kind, priv, indices) == ("index", "manage", ["x", "y", "z"])


def test_security_disabled_by_default_stays_open():
    node = Node()
    rc = RestController()
    register_handlers(node, rc)
    try:
        assert rc.dispatch("GET", "/", {}, None).status == 200
    finally:
        node.close()

"""Security v1: basic + API-key authn, role-based authz as a REST action
filter (VERDICT r4 item 9; ref: x-pack/.../authc/AuthenticationService.java:71,
authz/AuthorizationService.java:100)."""

import base64
import json

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest import RestController, register_handlers


def _basic(user, pw):
    return {"Authorization": "Basic " + base64.b64encode(
        f"{user}:{pw}".encode()).decode()}


@pytest.fixture()
def api():
    node = Node(settings=Settings({
        "xpack.security.enabled": "true",
        "bootstrap.password": "s3cret",
    }))
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, headers=None, params=None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body)
        return rc.dispatch(method, path, params or {}, body,
                           headers=headers)

    yield call, node
    node.close()


ELASTIC = _basic("elastic", "s3cret")


def test_anonymous_rejected_when_security_on(api):
    call, _ = api
    assert call("GET", "/").status == 401
    assert call("GET", "/x/_search").status == 401
    r = call("GET", "/", headers=ELASTIC)
    assert r.status == 200


def test_wrong_password_and_unknown_user_401(api):
    call, _ = api
    assert call("GET", "/", headers=_basic("elastic", "bad")).status == 401
    assert call("GET", "/", headers=_basic("nobody", "x")).status == 401


def test_authenticate_endpoint(api):
    call, _ = api
    r = call("GET", "/_security/_authenticate", headers=ELASTIC)
    assert r.status == 200
    assert r.body["username"] == "elastic"
    assert "superuser" in r.body["roles"]


def test_authz_matrix_reader_vs_writer(api):
    """The VERDICT's authz matrix: per-(role, action) allow/deny over
    index patterns."""
    call, _ = api
    # roles + users via the superuser
    assert call("PUT", "/_security/role/logs_reader", {
        "indices": [{"names": ["logs-*"], "privileges": ["read"]}]},
        headers=ELASTIC).status == 200
    assert call("PUT", "/_security/role/logs_writer", {
        "indices": [{"names": ["logs-*"],
                     "privileges": ["read", "write", "create_index"]}]},
        headers=ELASTIC).status == 200
    assert call("PUT", "/_security/user/bob", {
        "password": "bobpass", "roles": ["logs_reader"]},
        headers=ELASTIC).status == 200
    assert call("PUT", "/_security/user/amy", {
        "password": "amypass", "roles": ["logs_writer"]},
        headers=ELASTIC).status == 200
    call("PUT", "/logs-1", {}, headers=ELASTIC)
    call("PUT", "/secret-1", {}, headers=ELASTIC)
    call("PUT", "/logs-1/_doc/1", {"f": "v"}, headers=ELASTIC)
    call("POST", "/logs-1/_refresh", headers=ELASTIC)

    BOB = _basic("bob", "bobpass")
    AMY = _basic("amy", "amypass")
    matrix = [
        # (user, method, path, body, expected)
        (BOB, "GET", "/logs-1/_search", None, 200),
        (BOB, "GET", "/logs-1/_doc/1", None, 200),
        (BOB, "PUT", "/logs-1/_doc/2", {"f": "x"}, 403),
        (BOB, "GET", "/secret-1/_search", None, 403),
        (BOB, "PUT", "/logs-9", {}, 403),              # create_index
        (BOB, "DELETE", "/logs-1", None, 403),
        (BOB, "GET", "/_cluster/health", None, 403),   # cluster priv
        (AMY, "PUT", "/logs-1/_doc/2", {"f": "x"}, 201),
        (AMY, "PUT", "/logs-9", {}, 200),
        (AMY, "GET", "/logs-1/_search", None, 200),
        (AMY, "PUT", "/secret-1/_doc/1", {"f": "x"}, 403),
        (AMY, "DELETE", "/logs-1", None, 403),         # needs delete_index
        (AMY, "PUT", "/_security/user/eve",
         {"password": "p", "roles": []}, 403),         # manage_security
    ]
    for user, method, path, body, expect in matrix:
        r = call(method, path, body, headers=user)
        assert r.status == expect, (method, path, r.status, r.body)


def test_bulk_target_scoped_by_role(api):
    call, _ = api
    call("PUT", "/_security/role/lw", {
        "indices": [{"names": ["logs-*"], "privileges": ["write"]}]},
        headers=ELASTIC)
    call("PUT", "/_security/user/w1", {"password": "pw", "roles": ["lw"]},
         headers=ELASTIC)
    call("PUT", "/logs-a", {}, headers=ELASTIC)
    call("PUT", "/other", {}, headers=ELASTIC)
    W = _basic("w1", "pw")
    ok = '{"index":{"_index":"logs-a","_id":"1"}}\n{"f":"v"}\n'
    assert call("POST", "/_bulk", ok, headers=W).status == 200
    # a bulk smuggling a write to an out-of-scope index is rejected whole
    bad = ('{"index":{"_index":"logs-a","_id":"2"}}\n{"f":"v"}\n'
           '{"index":{"_index":"other","_id":"1"}}\n{"f":"v"}\n')
    assert call("POST", "/_bulk", bad, headers=W).status == 403


def test_api_key_roundtrip_and_invalidation(api):
    call, _ = api
    r = call("POST", "/_security/api_key", {"name": "ci"}, headers=ELASTIC)
    assert r.status == 200
    encoded = r.body["encoded"]
    key_hdr = {"Authorization": f"ApiKey {encoded}"}
    assert call("GET", "/_cluster/health", headers=key_hdr).status == 200
    auth = call("GET", "/_security/_authenticate", headers=key_hdr)
    assert auth.body["authentication_type"] == "api_key"
    call("DELETE", "/_security/api_key", {"id": r.body["id"]},
         headers=ELASTIC)
    assert call("GET", "/_cluster/health", headers=key_hdr).status == 401


def test_api_key_with_restricted_role_descriptors(api):
    call, _ = api
    call("PUT", "/logs-k", {}, headers=ELASTIC)
    r = call("POST", "/_security/api_key", {
        "name": "ro", "role_descriptors": {
            "ro": {"indices": [{"names": ["logs-*"],
                                "privileges": ["read"]}]}}},
        headers=ELASTIC)
    hdr = {"Authorization": f"ApiKey {r.body['encoded']}"}
    assert call("GET", "/logs-k/_search", headers=hdr).status == 200
    assert call("PUT", "/logs-k/_doc/1", {"f": "v"},
                headers=hdr).status == 403


def test_anonymous_roles_grant_configured_access():
    node = Node(settings=Settings({
        "xpack.security.enabled": "true",
        "xpack.security.authc.anonymous.roles": "monitoring_user",
    }))
    rc = RestController()
    register_handlers(node, rc)
    try:
        r = rc.dispatch("GET", "/_cluster/health", {}, None)
        assert r.status == 200                  # monitor granted anonymously
        r = rc.dispatch("PUT", "/idx", {}, "{}")
        assert r.status == 403                  # but nothing else
    finally:
        node.close()


def test_security_disabled_by_default_stays_open():
    node = Node()
    rc = RestController()
    register_handlers(node, rc)
    try:
        assert rc.dispatch("GET", "/", {}, None).status == 200
    finally:
        node.close()

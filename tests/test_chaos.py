"""Acked-writes chaos suite (PR 8): deterministic seeded scenarios
interleaving bulk streams with primary kills, promotions, crash–restarts,
and injected durability faults.

THE invariant, asserted through the linearizability checker's sequential
spec (testing/chaos.AckedRegisterSpec): every write the coordinator ACKED
is durable and readable afterwards. A write that never acked may vanish
(that is what unacked means); an acked write lost — or a read observing a
value no linearization explains — fails the history check.

Everything here is synchronous by construction (LocalStateStore drains
state updates and their deferred recoveries on the submitting thread), so
the scenarios are deterministic without sleeps or polling; the only
randomness is the seeded storm generator (ES_TPU_FAULTS_SEED).
"""

import random

import pytest

from elasticsearch_tpu.common import faults
from elasticsearch_tpu.common.durability import (
    durability_stats, reset_for_tests,
)
from elasticsearch_tpu.common.faults import inject
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.parallel.routing import shard_for_id
from elasticsearch_tpu.testing.chaos import (
    AckedWriteHistory, CrashRestartCluster,
)

pytestmark = pytest.mark.chaos

MAPPINGS = {"properties": {"n": {"type": "integer"},
                           "body": {"type": "text"}}}


@pytest.fixture(autouse=True)
def _clean():
    reset_for_tests()
    yield
    faults.clear()
    reset_for_tests()


def make_cluster(tmp_path, n_data=3, shards=1, replicas=1, index="docs",
                 settings=None):
    names = ["m0"] + [f"d{i}" for i in range(n_data)]
    cluster = CrashRestartCluster(names, str(tmp_path),
                                  roles={"m0": ("master",)})
    body = {"settings": {"number_of_shards": shards,
                         "number_of_replicas": replicas,
                         **(settings or {})},
            "mappings": MAPPINGS}
    cluster.master().create_index(index, body)
    return cluster


def acked_bulk(cluster, history, ops, index="docs", **kw):
    """Run one coordinator bulk, recording invoke/ack per op in the
    history. Returns the set of doc ids that were acked."""
    # the register value is the doc's `n` field (hashable, and what the
    # final reads observe)
    pending = [(op, history.invoke(op["id"],
                                   "delete" if op["op"] == "delete"
                                   else "write",
                                   (op.get("source") or {}).get("n")))
               for op in ops]
    resp = cluster.master().bulk(index, list(ops), **kw)
    acked = set()
    for (op, op_id), item in zip(pending, resp["items"]):
        if item is not None and "error" not in item:
            history.respond(op["id"], op_id)
            acked.add(op["id"])
    return acked


def write_op(doc_id, value):
    return {"op": "index", "id": doc_id,
            "source": {"n": value, "body": f"v{value}"}}


def final_reads(cluster, history, doc_ids, index="docs"):
    for d in sorted(doc_ids):
        src = cluster.read_doc(index, d)
        history.record_read(d, None if src is None else src["n"])


def node_of_copy(cluster, index, sid, primary):
    for r in cluster.store.current().shard_copies(index, sid):
        if r.primary == primary and r.node_id is not None \
                and r.state == "STARTED":
            return r.node_id
    return None


# --------------------------------------------------------------- scenarios


def test_primary_kill_mid_bulk_stream(tmp_path):
    """Scenario 1: the primary dies between bulks; promotion + the
    coordinator's stale-routing retry keep every acked write readable."""
    cluster = make_cluster(tmp_path)
    history = AckedWriteHistory()
    docs = [f"doc{i}" for i in range(8)]
    acked_bulk(cluster, history, [write_op(d, 1) for d in docs])
    victim = node_of_copy(cluster, "docs", 0, primary=True)
    cluster.crash(victim)
    acked_bulk(cluster, history, [write_op(d, 2) for d in docs])
    final_reads(cluster, history, docs)
    assert history.check() == []


def test_kill_during_recovery_finalize_cleans_ghost(tmp_path):
    """Scenario 2: the recovery RPC sequence dies at finalize (@4 across
    prepare/segments/ops/finalize); the target cancels its tracking on the
    source (no ghost pinning the global checkpoint) and the retry brings
    the copy in-sync."""
    cluster = make_cluster(tmp_path)
    history = AckedWriteHistory()
    docs = [f"doc{i}" for i in range(6)]
    acked_bulk(cluster, history, [write_op(d, 1) for d in docs])
    replica_holder = node_of_copy(cluster, "docs", 0, primary=False)
    with inject("rpc_recovery:raise@4x1"):
        # the crash triggers reallocation + the (faulted) recovery, all
        # synchronously inside report_node_left
        cluster.crash(replica_holder)
    stats = durability_stats()
    assert stats["ghost_cleanups"] == 1
    assert stats["recoveries_failed"] >= 1
    assert stats["recoveries_retried"] >= 1
    inst = cluster.primary_instance("docs", docs[0])
    assert inst.tracker.tracked_ids == inst.tracker.in_sync_ids
    assert len(inst.tracker.in_sync_ids) == 2   # primary + recovered copy
    acked_bulk(cluster, history, [write_op(d, 2) for d in docs])
    final_reads(cluster, history, docs)
    assert history.check() == []


def test_fsync_fault_fails_shard_never_acks_broken_wal(tmp_path):
    """Scenario 3: a translog fsync fault on the primary fails the copy via
    the master (promotion + reallocation, no wedged shard) and the
    coordinator's retry lands the write on the NEW primary — the broken
    WAL never acked anything."""
    cluster = make_cluster(tmp_path)
    history = AckedWriteHistory()
    docs = [f"doc{i}" for i in range(4)]
    acked_bulk(cluster, history, [write_op(d, 1) for d in docs])
    old_primary = node_of_copy(cluster, "docs", 0, primary=True)
    with inject("translog_fsync:raise@1x1"):
        acked = acked_bulk(cluster, history, [write_op("k", 9)])
    assert acked == {"k"}                      # retried onto the new primary
    stats = durability_stats()
    assert stats["fsync_shard_failures"] == 1
    assert stats["fsync_failures"] >= 1
    new_primary = node_of_copy(cluster, "docs", 0, primary=True)
    assert new_primary != old_primary          # the master reallocated
    final_reads(cluster, history, docs + ["k"])
    assert history.check() == []


def test_fsync_fault_visible_in_nodes_stats_section(tmp_path):
    """Scenario 3b: the tpu_durability stats section carries the ladder's
    counters (same helper GET /_nodes/stats renders)."""
    from elasticsearch_tpu.rest.handlers import _tpu_durability_stats

    cluster = make_cluster(tmp_path)
    with inject("translog_fsync:raise@1x1"):
        cluster.master().bulk("docs", [write_op("k", 1)])
    out = _tpu_durability_stats()
    for key in ("fsync_failures", "fsync_shard_failures", "translog_syncs",
                "replication_retries", "recoveries_started",
                "ghost_cleanups", "open_translogs", "max_ops_since_sync"):
        assert key in out
    assert out["fsync_shard_failures"] == 1
    assert out["translog_syncs"] > 0


def test_crash_restart_replays_translog(tmp_path):
    """Scenario 4: a single-copy node crashes before any flush and comes
    back from disk: the commit load + translog replay restore every acked
    write (the master never noticed — report=False models a fast restart)."""
    cluster = make_cluster(tmp_path, n_data=1, replicas=0)
    history = AckedWriteHistory()
    docs = [f"doc{i}" for i in range(10)]
    acked_bulk(cluster, history, [write_op(d, 7) for d in docs])
    cluster.crash("d0", report=False)
    cluster.restart("d0")
    assert durability_stats()["translog_replays"] >= 1
    final_reads(cluster, history, docs)
    assert history.check() == []


def test_segment_commit_fault_then_crash_restart(tmp_path):
    """Scenario 5: flush dies at the segment_commit site, leaving the docs
    translog-only; a crash + restart still recovers them — the WAL covers
    everything the failed commit did not."""
    cluster = make_cluster(tmp_path, n_data=1, replicas=0)
    history = AckedWriteHistory()
    docs = [f"doc{i}" for i in range(5)]
    acked_bulk(cluster, history, [write_op(d, 3) for d in docs])
    inst = cluster.node("d0").shard_service.shards[("docs", 0)]
    with inject("segment_commit:raise@1x1"):
        with pytest.raises(OSError):
            inst.engine.flush()
    assert durability_stats()["segment_commit_failures"] == 1
    cluster.crash("d0", report=False)
    cluster.restart("d0")
    final_reads(cluster, history, docs)
    assert history.check() == []


def test_async_durability_exposure_is_bounded(tmp_path, monkeypatch):
    """Scenario 6: under async durability a crash may lose the unsynced
    tail — but never more than ES_TPU_TRANSLOG_SYNC_OPS ops of it."""
    monkeypatch.setenv("ES_TPU_TRANSLOG_SYNC_OPS", "4")
    cluster = make_cluster(
        tmp_path, n_data=1, replicas=0,
        settings={"index.translog.durability": "async"})
    docs = [f"doc{i}" for i in range(10)]
    for d in docs:
        cluster.master().bulk("docs", [write_op(d, 5)])
    # 10 appends with a window of 4: synced through op 8; ops 9-10 exposed
    assert durability_stats()["max_ops_since_sync"] <= 4
    cluster.crash("d0", report=False)
    cluster.restart("d0")
    survived = [d for d in docs
                if cluster.read_doc("docs", d) is not None]
    assert len(survived) >= len(docs) - 4
    assert survived == docs[:len(survived)]    # a PREFIX: no holes


def test_promotion_under_divergence_rolls_back_restarted_copy(tmp_path):
    """Scenario 7: the primary dies holding a durable-but-unreplicated
    tail; the replica is promoted; the restarted old primary must roll its
    divergent tail back to the promoted primary's history (recovery reuses
    the resync machinery) — reads never resurrect the unacked value."""
    cluster = make_cluster(tmp_path, n_data=2)
    history = AckedWriteHistory()
    acked_bulk(cluster, history, [write_op("k", 1)])
    old_primary = node_of_copy(cluster, "docs", 0, primary=True)
    inst = cluster.node(old_primary).shard_service.shards[("docs", 0)]
    # a write that reached (and fsynced on) the primary but never
    # replicated and never acked: invoke with no response
    history.invoke("k", "write", 2)
    with inst.lock:
        inst.engine.index("k", {"n": 2, "body": "v2"})
    cluster.crash(old_primary)                 # replica promoted
    restarted = cluster.restart(old_primary)   # rejoins as replica
    sid = shard_for_id("k", 1)
    r_inst = restarted.shard_service.shards[("docs", sid)]
    assert r_inst.engine.get("k")["_source"]["n"] == 1   # tail rolled back
    acked_bulk(cluster, history, [write_op("k", 3)])
    assert r_inst.engine.get("k")["_source"]["n"] == 3   # replication works
    final_reads(cluster, history, ["k"])
    assert history.check() == []


def test_replica_bulk_transient_blip_is_retried(tmp_path):
    """Scenario 8: one injected replica-RPC blip costs a retry, not the
    copy — the replica stays in-sync and holds the write."""
    cluster = make_cluster(tmp_path)
    history = AckedWriteHistory()
    acked_bulk(cluster, history, [write_op("a", 1)])
    replica_holder = node_of_copy(cluster, "docs", 0, primary=False)
    with inject(f"rpc_replica_bulk#{replica_holder}:raise@1x1"):
        acked_bulk(cluster, history, [write_op("b", 1)])
    stats = durability_stats()
    assert stats["replication_retries"] == 1
    assert stats["replication_failures"] == 0
    inst = cluster.primary_instance("docs", "b")
    assert len(inst.tracker.in_sync_ids) == 2  # still in-sync
    r_inst = cluster.node(replica_holder).shard_service.shards[("docs", 0)]
    assert r_inst.engine.get("b") is not None
    final_reads(cluster, history, ["a", "b"])
    assert history.check() == []


def test_replica_bulk_persistent_failure_fails_copy_not_acks(tmp_path):
    """Scenario 9: a persistently unreachable replica is failed to the
    master after the one transient retry; the write still acks (the
    primary + reallocated copy carry it) and no acked write is lost."""
    cluster = make_cluster(tmp_path)
    history = AckedWriteHistory()
    acked_bulk(cluster, history, [write_op("a", 1)])
    replica_holder = node_of_copy(cluster, "docs", 0, primary=False)
    with inject(f"rpc_replica_bulk#{replica_holder}:raise@1xinf"):
        acked = acked_bulk(cluster, history, [write_op("b", 1)])
    assert acked == {"b"}
    stats = durability_stats()
    assert stats["replication_retries"] >= 1
    assert stats["replication_failures"] == 1
    # the faulted copy was removed and a replacement recovered in-sync
    inst = cluster.primary_instance("docs", "b")
    assert len(inst.tracker.in_sync_ids) == 2
    acked_bulk(cluster, history, [write_op("c", 1)])
    final_reads(cluster, history, ["a", "b", "c"])
    assert history.check() == []


def test_seeded_chaos_storm(tmp_path):
    """Scenario 10: the storm — seeded random interleaving of bulk
    streams, primary/replica kills, restarts, and bounded durability
    faults across a 2-shard/1-replica index. Deterministic under
    ES_TPU_FAULTS_SEED; zero acked-write loss, every final read
    linearizable."""
    seed = knob("ES_TPU_FAULTS_SEED") or 8
    rng = random.Random(seed)
    cluster = make_cluster(tmp_path, n_data=3, shards=2, replicas=1)
    history = AckedWriteHistory()
    keyspace = [f"doc{i}" for i in range(12)]
    value = 0
    down = None
    for rnd in range(8):
        value += 1
        batch = [write_op(d, value)
                 for d in rng.sample(keyspace, rng.randint(3, 8))]
        if rnd in (2, 5):
            spec = rng.choice(["translog_fsync:raise@1x1",
                               "rpc_replica_bulk:raise@1x1"])
            with inject(spec):
                acked_bulk(cluster, history, batch)
        else:
            acked_bulk(cluster, history, batch)
        if rnd in (1, 4) and down is None:
            down = rng.choice(sorted(
                n.node_name for n in cluster.nodes
                if n.node_name != "m0"))
            cluster.crash(down)
        elif down is not None:
            cluster.restart(down)
            down = None
    if down is not None:
        cluster.restart(down)
    final_reads(cluster, history, keyspace)
    assert history.check() == []
    assert durability_stats()["recoveries_started"] >= 1


def test_disk_corruption_promotes_replica_and_heals_copy(tmp_path):
    """Scenario 11 (integrity plane, PR 15): a committed primary segment
    rots on disk while the node is down; the restarted node discovers the
    flip at commit load (checksum footer), refuses the copy, and the
    master promotes the replica; the corrupted store is quarantined and
    re-recovers from the healthy peer. Writes keep flowing throughout and
    the acked-write history stays linearizable."""
    import glob
    import os

    from elasticsearch_tpu.common import integrity

    integrity.reset_for_tests()
    cluster = make_cluster(tmp_path)
    history = AckedWriteHistory()
    docs = [f"doc{i}" for i in range(10)]
    acked_bulk(cluster, history, [write_op(d, 1) for d in docs])
    victim = node_of_copy(cluster, "docs", 0, primary=True)
    survivor = node_of_copy(cluster, "docs", 0, primary=False)
    cluster.primary_instance("docs", docs[0]).engine.flush()
    # fast restart (report=False): the master never saw the crash, so the
    # corruption itself — not failure detection — must fail the copy
    cluster.crash(victim, report=False)
    seg = glob.glob(os.path.join(
        str(tmp_path), victim, "docs", "0", "segments", "*.seg"))[0]
    with open(seg, "rb") as f:
        data = f.read()
    with open(seg, "wb") as f:
        f.write(integrity.bitflip(data))
    cluster.restart(victim)
    stats = integrity.integrity_stats()
    assert stats["segments_corrupted"] >= 1
    assert stats["shards_failed_corrupt"] >= 1
    assert stats["copies_quarantined"] >= 1
    assert node_of_copy(cluster, "docs", 0, primary=True) == survivor
    # the healed copy is tracked in-sync and serves subsequent writes
    inst = cluster.primary_instance("docs", docs[0])
    assert len(inst.tracker.in_sync_ids) == 2
    acked_bulk(cluster, history, [write_op(d, 2) for d in docs[:4]])
    final_reads(cluster, history, docs)
    assert history.check() == []


def test_leader_cluster_crash_restart_mid_replication(tmp_path, monkeypatch):
    """Scenario 12 (cross-cluster plane, PR 20): the LEADER cluster
    crash-restarts mid-replication while the follower cluster keeps
    serving reads from what it already pulled. Invariants: zero acked
    leader writes lost (history linearizable including post-convergence
    follower reads), mid-outage follower reads are exactly the pre-crash
    snapshot, and after heal the follower converges to the leader's
    global checkpoint."""
    monkeypatch.setenv("ES_TPU_CCR_POLL_MS", "0")        # manual pump
    monkeypatch.setenv("ES_TPU_REMOTE_BACKOFF_MS", "0")
    leader = CrashRestartCluster(
        ["L-m0", "L-d0", "L-d1"], str(tmp_path / "L"),
        roles={"L-m0": ("master",)})
    follower = CrashRestartCluster(
        ["F-m0", "F-d0"], str(tmp_path / "F"),
        roles={"F-m0": ("master",)})
    leader.master().create_index("docs", {
        "settings": {"number_of_shards": 1, "number_of_replicas": 1},
        "mappings": MAPPINGS})
    for n in follower.nodes:
        n.remotes.register_remote("leader", leader.channels,
                                  ["L-d0", "L-d1"], skip_unavailable=True)
    history = AckedWriteHistory()
    docs = [f"doc{i}" for i in range(8)]

    def pump():
        total = 0
        for n in follower.nodes:
            while True:
                moved = n.ccr.poll_once()
                total += moved
                if moved == 0:
                    break
        return total

    # phase 1: writes replicate to the follower, which serves them
    acked_bulk(leader, history, [write_op(d, 1) for d in docs])
    follower.master().ccr.follow("docs_copy", "leader", "docs")
    assert pump() == len(docs)
    snapshot = {d: follower.read_doc("docs_copy", d)["n"] for d in docs}
    assert set(snapshot.values()) == {1}

    # phase 2: more acked writes land on the leader, and BEFORE the
    # follower pulls them the whole leader data plane crashes
    acked_bulk(leader, history, [write_op(d, 2) for d in docs[:5]])
    leader.primary_instance("docs", docs[0]).engine.flush()
    leader.crash("L-d0", report=False)
    leader.crash("L-d1", report=False)

    # the follower keeps serving its pre-crash snapshot; the pull loop
    # records the outage and keeps the loop alive — never raises
    assert pump() == 0
    for d in docs:
        assert follower.read_doc("docs_copy", d)["n"] == snapshot[d]
    st = follower.master().ccr.follower_stats("docs_copy")["indices"][0]
    assert "last_error" in st

    # heal: the leader restarts from disk (commit load + translog replay
    # restores every acked write), takes more writes, and the follower
    # catches all the way up to the leader's global checkpoint
    leader.restart("L-d0")
    leader.restart("L-d1")
    acked_bulk(leader, history, [write_op(d, 3) for d in docs[:2]])
    assert pump() > 0
    assert pump() == 0                       # converged: nothing left
    f_inst = follower.primary_instance("docs_copy", docs[0])
    l_inst = leader.primary_instance("docs", docs[0])
    assert f_inst.engine.local_checkpoint \
        == l_inst.tracker.global_checkpoint
    st = follower.master().ccr.follower_stats("docs_copy")["indices"][0]
    assert all(s["lag_ops"] == 0 for s in st["shards"])

    # the acked-write history — leader final reads AND post-convergence
    # follower reads — is linearizable: nothing acked was lost anywhere
    final_reads(leader, history, docs)
    for d in sorted(docs):
        src = follower.read_doc("docs_copy", d)
        history.record_read(d, None if src is None else src["n"])
    assert history.check() == []
